"""Zero-copy columnar image plane (ISSUE 18; docs/PERF.md "Columnar
data plane").

Pins the tentpole contract: the columnar struct-column builder is
logically identical to the per-row path (so `columnar_images` on/off and
`decode_workers` on/off are bit-identical end to end), decode-pool
adoption hands the builder consecutive views of ONE flat buffer that
wrap into Arrow zero-copy, corrupt blobs degrade identically on both
paths, fused device preprocess matches host-f32 staging per registry
normalize mode (fp32 exact, bf16 within the 0.05 contract), and the
host ships raw uint8 bytes only — no float32 staging, no per-row struct
construction on the ingest spine.
"""

import numpy as np
import pyarrow as pa
import pytest

from sparkdl_tpu.core import decode_pool
from sparkdl_tpu.core import executor as device_executor
from sparkdl_tpu.core.model_function import ModelFunction, TensorSpec
from sparkdl_tpu.engine.dataframe import EngineConfig
from sparkdl_tpu.image import imageIO
from sparkdl_tpu.ml.image_transformer import (
    TPUImageTransformer,
    _resize_uniform_batch,
)
from sparkdl_tpu.models.registry import PREPROCESS_MODES


@pytest.fixture(autouse=True)
def _restore_engine_config():
    saved = EngineConfig.snapshot()
    yield
    EngineConfig.restore(saved)
    decode_pool.shutdown()


@pytest.fixture
def uniform_image_dir(tmp_path, rng):
    """8 uniform 10x12 JPEGs — every partition decodes uniform, so the
    columnar builder engages (ragged dirs fall back per row)."""
    from PIL import Image

    d = tmp_path / "uniform"
    d.mkdir()
    for i in range(8):
        arr = rng.integers(0, 255, size=(10, 12, 3), dtype=np.uint8)
        Image.fromarray(arr).save(d / f"img_{i}.png")
    return d


# ---------------------------------------------------------------------------
# builder: logical equality + zero-copy wrap
# ---------------------------------------------------------------------------


def test_builder_matches_per_row_path(rng):
    arrays = [rng.integers(0, 255, (7, 5, 3), dtype=np.uint8)
              for _ in range(5)]
    arrays[2] = None  # a degraded row interleaves as null on both paths
    origins = [f"file:/img/{i}.png" for i in range(5)]

    col = imageIO.imageArraysToStructColumn(arrays, origins)
    EngineConfig.columnar_images = False
    ref = imageIO.imageArraysToStructColumn(arrays, origins)

    assert col.type == ref.type == imageIO.imageSchema
    assert col.to_pylist() == ref.to_pylist()
    # both feed the SAME zero-copy consumer downstream
    fast = imageIO.arrowImageBatch(col)
    fast_ref = imageIO.arrowImageBatch(ref)
    assert fast is not None and fast_ref is not None
    np.testing.assert_array_equal(fast[0], fast_ref[0])
    np.testing.assert_array_equal(fast[1], fast_ref[1])


def test_builder_wraps_contiguous_views_zero_copy(rng):
    """Consecutive views over one flat uint8 base (exactly what
    decode-pool adoption produces) must wrap WITHOUT copying: the Arrow
    data child's buffer address is the base's address."""
    h, w, c = 6, 4, 3
    row = h * w * c
    flat = rng.integers(0, 255, row * 3, dtype=np.uint8)
    views = [flat[i * row:(i + 1) * row].reshape(h, w, c) for i in range(3)]

    col = imageIO.imageArraysToStructColumn(views, ["a", "b", "c"])
    data_buf = col.field("data").buffers()[2]
    assert data_buf.address == flat.__array_interface__["data"][0]
    # and the round trip reads the same pixels
    batch, valid = imageIO.arrowImageBatch(col)
    np.testing.assert_array_equal(batch,
                                  flat.reshape(3, h, w, c))


def test_builder_ragged_and_odd_input_falls_back(rng):
    ragged = [rng.integers(0, 255, (4, 4, 3), dtype=np.uint8),
              rng.integers(0, 255, (5, 4, 3), dtype=np.uint8)]
    col = imageIO.imageArraysToStructColumn(ragged, ["a", "b"])
    EngineConfig.columnar_images = False
    ref = imageIO.imageArraysToStructColumn(ragged, ["a", "b"])
    assert col.to_pylist() == ref.to_pylist()

    all_null = imageIO.imageArraysToStructColumn([None, None], ["a", "b"])
    assert all_null.to_pylist() == [None, None]
    assert all_null.type == imageIO.imageSchema


def test_decode_pool_adoption_feeds_builder_zero_copy(rng):
    """Pool adoption = ONE memcpy out of shm; the resulting views share
    one base the builder detects, so pool→Arrow adds no further copy."""
    arrays = [rng.integers(0, 255, (5, 5, 3), dtype=np.uint8)
              for _ in range(3)]
    meta = decode_pool._pack_result(arrays, [0.0] * 3, 4242)
    adopted = decode_pool._adopt_result(meta)
    base = adopted[0].base
    assert all(a.base is base for a in adopted)
    for got, want in zip(adopted, arrays):
        np.testing.assert_array_equal(got, want)

    col = imageIO.imageArraysToStructColumn(adopted, ["x", "y", "z"])
    assert (col.field("data").buffers()[2].address
            == base.__array_interface__["data"][0])


# ---------------------------------------------------------------------------
# end-to-end bit-identity: pool on/off x columnar on/off
# ---------------------------------------------------------------------------


def _collect_images(image_dir):
    df = imageIO.readImages(str(image_dir))
    return df.collect()


@pytest.mark.parametrize("corrupt", [False, True])
def test_read_images_bit_identical_across_matrix(uniform_image_dir,
                                                 corrupt):
    """readImages output is bit-identical across decode pool on/off x
    columnar on/off; with a corrupt blob, every combo degrades the SAME
    row to null and records the SAME decode_degraded count."""
    from sparkdl_tpu.core.health import HealthMonitor

    if corrupt:
        (uniform_image_dir / "aa_corrupt.png").write_bytes(b"not a png")

    results = {}
    for workers in (0, 2):
        for columnar in (True, False):
            EngineConfig.decode_workers = workers
            EngineConfig.columnar_images = columnar
            with HealthMonitor() as mon:
                rows = _collect_images(uniform_image_dir)
            decode_pool.shutdown()
            results[(workers, columnar)] = (
                rows, mon.count("decode_degraded"))

    baseline_rows, baseline_degraded = results[(0, False)]
    assert len(baseline_rows) == (9 if corrupt else 8)
    assert baseline_degraded == (1 if corrupt else 0)
    if corrupt:
        by_path = {r["filePath"]: r["image"] for r in baseline_rows}
        nulls = [p for p, img in by_path.items() if img is None]
        assert len(nulls) == 1 and nulls[0].endswith("aa_corrupt.png")
    for key, (rows, degraded) in results.items():
        assert rows == baseline_rows, f"combo {key} diverged"
        assert degraded == baseline_degraded, f"combo {key} health diverged"


# ---------------------------------------------------------------------------
# fused preprocess: per-normalize-mode equivalence
# ---------------------------------------------------------------------------


def _mode_model(mode_fn, size):
    """Forward = per-image channel means after the mode's normalize —
    sensitive to scale, sign, and channel order (catches a BGR flip)."""
    import jax.numpy as jnp

    mf = ModelFunction(
        lambda vs, x: jnp.mean(x, axis=(1, 2)) + vs,
        jnp.zeros((3,), jnp.float32),
        TensorSpec((None,) + size + (3,), "float32"),
        name=f"mode_{id(mode_fn)}")
    return mf.with_preprocess(mode_fn)


@pytest.mark.parametrize("mode", sorted(PREPROCESS_MODES))
def test_fused_preprocess_matches_host_f32_staging(rng, mode):
    """fp32: shipping raw uint8 at SOURCE size through the fused
    resize+normalize program is EXACT vs staging float32 host-side into
    the same program (uint8→f32 cast is exact for 0-255)."""
    EngineConfig.fused_preprocess = True
    run = _mode_model(PREPROCESS_MODES[mode], (6, 6))
    stacked = rng.integers(0, 255, (4, 9, 8, 3), dtype=np.uint8)

    shipped, fused = _resize_uniform_batch(stacked, (6, 6), run)
    assert shipped.dtype == np.uint8 and shipped is stacked  # no host work
    y_fused = np.asarray(device_executor.execute(fused, shipped,
                                                 batch_size=4))
    y_ref = np.asarray(device_executor.execute(
        fused, stacked.astype(np.float32), batch_size=4))
    np.testing.assert_array_equal(y_fused, y_ref)


@pytest.mark.parametrize("mode", sorted(PREPROCESS_MODES))
def test_fused_preprocess_bf16_within_contract(rng, mode):
    """bf16: the fused path obeys the PR 12 precision contract — within
    0.05 of the fp32 result, scaled to the mode's output magnitude."""
    EngineConfig.fused_preprocess = True
    run = _mode_model(PREPROCESS_MODES[mode], (6, 6))
    stacked = rng.integers(0, 255, (4, 9, 8, 3), dtype=np.uint8)

    shipped, fused = _resize_uniform_batch(stacked, (6, 6), run)
    y32 = np.asarray(device_executor.execute(fused, shipped, batch_size=4))
    EngineConfig.inference_precision = "bfloat16"
    y16 = np.asarray(device_executor.execute(fused, shipped, batch_size=4),
                     dtype=np.float32)
    scale = float(np.max(np.abs(y32))) + 1.0
    np.testing.assert_allclose(y16, y32, atol=0.05 * scale)


def test_fused_off_restores_host_resize_policy(rng):
    """fused_preprocess=False keeps the legacy r3 byte-minimizing
    policy: downscales resize on host, the model is left alone."""
    EngineConfig.fused_preprocess = False
    run = _mode_model(PREPROCESS_MODES["identity"], (6, 6))
    stacked = rng.integers(0, 255, (4, 9, 8, 3), dtype=np.uint8)
    shipped, run_out = _resize_uniform_batch(stacked, (6, 6), run)
    assert shipped.shape == (4, 6, 6, 3)  # host resized
    assert run_out is run  # no device resize composed


# ---------------------------------------------------------------------------
# acceptance: host ships uint8 only, zero per-row struct construction
# ---------------------------------------------------------------------------


def test_host_ships_uint8_no_per_row_structs(uniform_image_dir,
                                             monkeypatch):
    """The ingest spine's acceptance assert: on the columnar path the
    executor receives RAW UINT8 at source size (no float32 staging, no
    host resize) and imageArrayToStruct never runs during ingest."""
    struct_calls = []
    real_struct = imageIO.imageArrayToStruct
    monkeypatch.setattr(
        imageIO, "imageArrayToStruct",
        lambda *a, **k: struct_calls.append(1) or real_struct(*a, **k))

    staged = []
    real_execute = device_executor.execute

    def capture(model, array, **kw):
        staged.append(np.asarray(array))
        return real_execute(model, array, **kw)

    monkeypatch.setattr(device_executor, "execute", capture)
    import sparkdl_tpu.ml.image_transformer as it_mod
    monkeypatch.setattr(it_mod.device_executor, "execute", capture)

    import jax.numpy as jnp
    mf = ModelFunction(
        lambda vs, x: x.reshape((x.shape[0], -1)) @ vs,
        jnp.ones((6 * 6 * 3, 2), jnp.float32) * 0.01,
        TensorSpec((None, 6, 6, 3), "float32"), name="u8_probe")

    df = imageIO.readImages(str(uniform_image_dir))
    t = TPUImageTransformer(inputCol="image", outputCol="f",
                            modelFunction=mf, batchSize=8)
    rows = t.transform(df).select("f").collect()

    assert len(rows) == 8 and all(r["f"] is not None for r in rows)
    assert staged, "executor.execute never saw the ingest batches"
    for arr in staged:
        assert arr.dtype == np.uint8, "host staged non-uint8 bytes"
        assert arr.shape[1:3] == (10, 12), "host resized before shipping"
    assert not struct_calls, (
        "per-row imageArrayToStruct ran on the columnar ingest spine")


def test_staged_bytes_counter_counts_uint8_payload(uniform_image_dir):
    """M_STAGED_BYTES totals exactly the raw uint8 payload bytes — the
    trajectory observable for float32-staging regressions."""
    from sparkdl_tpu.core import telemetry

    import jax.numpy as jnp
    mf = ModelFunction(
        lambda vs, x: x.reshape((x.shape[0], -1)) @ vs,
        jnp.ones((6 * 6 * 3, 2), jnp.float32) * 0.01,
        TensorSpec((None, 6, 6, 3), "float32"), name="u8_bytes")

    df = imageIO.readImages(str(uniform_image_dir))
    t = TPUImageTransformer(inputCol="image", outputCol="f",
                            modelFunction=mf, batchSize=8)
    with telemetry.Telemetry("columnar-bytes") as tel:
        rows = t.transform(df).select("f").collect()
    assert all(r["f"] is not None for r in rows)
    snap = tel.metrics.snapshot()
    staged = snap["counters"][telemetry.M_STAGED_BYTES]
    assert staged == 8 * 10 * 12 * 3  # raw uint8 pixels, nothing more
