"""imageIO tests — schema contract + codecs + readers (SURVEY.md §2.1 L3)."""

import numpy as np
import pyarrow as pa
import pytest

from sparkdl_tpu.image import imageIO


def test_schema_fields_match_reference_contract():
    assert imageIO.imageFields == [
        "origin", "height", "width", "nChannels", "mode", "data"]


def test_array_struct_roundtrip_uint8(rng):
    arr = rng.integers(0, 255, size=(17, 23, 3), dtype=np.uint8)
    struct = imageIO.imageArrayToStruct(arr, origin="mem")
    assert struct["mode"] == 16  # CV_8UC3
    assert struct["height"] == 17 and struct["width"] == 23
    back = imageIO.imageStructToArray(struct)
    np.testing.assert_array_equal(arr, back)


def test_array_struct_roundtrip_float32(rng):
    arr = rng.standard_normal((8, 9, 1)).astype(np.float32)
    struct = imageIO.imageArrayToStruct(arr)
    assert struct["mode"] == 5  # CV_32FC1
    back = imageIO.imageStructToArray(struct)
    np.testing.assert_array_equal(arr, back)


def test_2d_array_promoted_to_single_channel(rng):
    arr = rng.integers(0, 255, size=(5, 6), dtype=np.uint8)
    struct = imageIO.imageArrayToStruct(arr)
    assert struct["nChannels"] == 1 and struct["mode"] == 0


def test_unsupported_dtype_rejected():
    with pytest.raises(ValueError):
        imageIO.imageArrayToStruct(np.zeros((4, 4, 3), dtype=np.int64))
    with pytest.raises(ValueError):
        imageIO.imageTypeByCode(999)


def test_struct_array_arrow_roundtrip(rng):
    arr = rng.integers(0, 255, size=(4, 4, 3), dtype=np.uint8)
    struct = imageIO.imageArrayToStruct(arr, origin="x")
    pa_arr = pa.array([struct], type=imageIO.imageSchema)
    back = imageIO.imageStructToArray(pa_arr[0])
    np.testing.assert_array_equal(arr, back)


def test_resize_uint8(rng):
    arr = rng.integers(0, 255, size=(10, 20, 3), dtype=np.uint8)
    out = imageIO.resizeImageArray(arr, (5, 5))
    assert out.shape == (5, 5, 3) and out.dtype == np.uint8


def test_resize_float32(rng):
    arr = rng.standard_normal((10, 20, 3)).astype(np.float32)
    out = imageIO.resizeImageArray(arr, (4, 8))
    assert out.shape == (4, 8, 3) and out.dtype == np.float32


def test_batch_decode_with_resize(rng):
    structs = [
        imageIO.imageArrayToStruct(
            rng.integers(0, 255, size=(h, 12, 3), dtype=np.uint8))
        for h in (6, 9, 12)
    ]
    batch = imageIO.imageStructsToBatchArray(structs, target_size=(8, 8))
    assert batch.shape == (3, 8, 8, 3) and batch.dtype == np.float32


def test_read_images(tiny_image_dir):
    df = imageIO.readImages(str(tiny_image_dir))
    rows = df.collect()
    assert len(rows) == 5  # txt file is not listed
    ok = [r for r in rows if r["image"] is not None]
    assert len(ok) == 5
    first = ok[0]["image"]
    assert first["nChannels"] == 3
    decoded = imageIO.imageStructToArray(first)
    assert decoded.shape == (first["height"], first["width"], 3)


def test_read_images_undecodable_yields_null(tmp_path):
    bad = tmp_path / "bad.jpg"
    bad.write_bytes(b"this is not a jpeg")
    df = imageIO.readImages(str(tmp_path))
    rows = df.collect()
    assert len(rows) == 1 and rows[0]["image"] is None


def test_decode_image_file_resize(tiny_image_dir):
    files = imageIO.listImageFiles(str(tiny_image_dir))
    arr = imageIO.decodeImageFile(files[0], target_size=(16, 16))
    assert arr.shape == (16, 16, 3) and arr.dtype == np.uint8


def test_empty_staging_batch_keeps_nhwc_rank():
    out = imageIO.imageStructsToBatchArray([], target_size=(8, 8))
    assert out.shape == (0, 8, 8, 3)


def test_read_images_decode_is_lazy_and_parallel(tiny_image_dir):
    # The reader must not decode at construction time.
    df = imageIO.readImages(str(tiny_image_dir))
    assert df._materialized is None  # plan only
    assert df.count() == 5


def _write_fixtures(tmp_path, rng):
    from PIL import Image

    paths = []
    for i in range(4):
        arr = rng.integers(0, 255, size=(20 + 4 * i, 24, 3), dtype=np.uint8)
        p = tmp_path / f"b{i}.jpg"
        Image.fromarray(arr).save(p, quality=95)
        paths.append(str(p))
    p = tmp_path / "b_png.png"
    Image.fromarray(rng.integers(0, 255, size=(16, 16, 3),
                                 dtype=np.uint8)).save(p)
    paths.append(str(p))
    return paths


def test_decode_files_batch_matches_per_image(tmp_path, rng):
    """The partition batch-decode hot path must agree with the per-image
    decoder, and handle corrupt/missing/None URIs as null rows."""
    paths = _write_fixtures(tmp_path, rng)
    bad = tmp_path / "corrupt.jpg"
    bad.write_bytes(b"definitely not a jpeg")
    uris = paths + [str(bad), str(tmp_path / "missing.jpg"), None]
    out = imageIO.decodeImageFilesBatch(uris, target_size=(18, 18))
    assert len(out) == len(uris)
    assert out[-1] is None and out[-2] is None and out[-3] is None
    for uri, arr in zip(paths, out):
        assert arr is not None and arr.shape == (18, 18, 3)
        assert arr.dtype == np.uint8
        single = imageIO.decodeImageFile(uri, target_size=(18, 18))
        # same decoder family → same pixels (PIL fallback may differ by
        # resize rounding, tolerate 2 LSB)
        assert np.abs(arr.astype(int) - single.astype(int)).max() <= 2


def test_decode_bytes_batch_pil_fallback(tmp_path, rng, monkeypatch):
    """With the native library unavailable the batch path must still decode
    every blob (PIL, forced RGB)."""
    from PIL import Image

    from sparkdl_tpu.native import loader as native_loader

    monkeypatch.setattr(native_loader, "decode_batch_status",
                        lambda *a, **k: None)
    blobs = []
    for i in range(2):
        import io

        buf = io.BytesIO()
        Image.fromarray(rng.integers(0, 255, size=(12, 12, 3),
                                     dtype=np.uint8)).save(buf, format="PNG")
        blobs.append(buf.getvalue())
    # grayscale must come out 3-channel like the native path
    import io

    buf = io.BytesIO()
    Image.fromarray(rng.integers(0, 255, size=(12, 12),
                                 dtype=np.uint8)).save(buf, format="PNG")
    blobs.append(buf.getvalue())
    out = imageIO.decodeImageBytesBatch(blobs, target_size=(10, 10))
    assert all(a is not None and a.shape == (10, 10, 3) for a in out)


def test_struct_batch_array_preserves_uint8(rng):
    arrs = [rng.integers(0, 255, size=(8, 8, 3), dtype=np.uint8)
            for _ in range(3)]
    structs = [imageIO.imageArrayToStruct(a) for a in arrs]
    batch = imageIO.imageStructsToBatchArray(structs, dtype=None)
    assert batch.dtype == np.uint8
    np.testing.assert_array_equal(batch, np.stack(arrs))
    # mixed dtypes promote to float32
    structs.append(imageIO.imageArrayToStruct(
        rng.normal(size=(8, 8, 3)).astype(np.float32)))
    mixed = imageIO.imageStructsToBatchArray(structs, dtype=None)
    assert mixed.dtype == np.float32


def test_resize_batch_implementations_agree(rng):
    """numpy resizeBatchArray == native sdl_resize_batch (to uint8 rounding).

    Non-square source AND target so any H/W transpose in either
    implementation fails loudly.
    """
    from sparkdl_tpu.native import loader as native_loader

    batch = rng.integers(0, 255, size=(4, 40, 36, 3), dtype=np.uint8)
    npy = imageIO.resizeBatchArray(batch, (24, 28))
    assert npy.shape == (4, 24, 28, 3) and npy.dtype == np.uint8
    if native_loader.available():
        nat = native_loader.resize_batch(batch, (24, 28))
        assert nat is not None and nat.shape == npy.shape
        diff = np.abs(npy.astype(np.int32) - nat.astype(np.int32))
        assert diff.max() <= 2, f"native vs numpy resize diverge: {diff.max()}"


def test_resize_batch_float32_preserves_dtype(rng):
    batch = rng.uniform(0, 1, size=(3, 16, 12, 3)).astype(np.float32)
    out = imageIO.resizeBatchArray(batch, (8, 10))
    assert out.shape == (3, 8, 10, 3) and out.dtype == np.float32


def test_grayscale_channel_consistency_batch_vs_per_row(tmp_path):
    """ADVICE r2: the same grayscale input must yield the same channel
    count whether the batch decoder or the per-row path ran."""
    from PIL import Image

    rng = np.random.default_rng(3)
    p = tmp_path / "gray.png"
    Image.fromarray(rng.integers(0, 255, size=(20, 16), dtype=np.uint8),
                    mode="L").save(p)
    per_row = imageIO.decodeImageFile(str(p), target_size=(10, 8), channels=3)
    batch = imageIO.decodeImageFilesBatch([str(p)], target_size=(10, 8))[0]
    assert per_row.shape == batch.shape == (10, 8, 3)
    np.testing.assert_array_equal(per_row, batch)
    # channels=None preserves the source's own channel count
    preserved = imageIO.decodeImageFile(str(p))
    assert preserved.shape[2] == 1


def test_pil_decode_channels_rgba_and_invalid(tmp_path):
    from io import BytesIO

    from PIL import Image

    from sparkdl_tpu.image.imageIO import _pil_decode_channels

    rng = np.random.default_rng(4)
    buf = BytesIO()
    Image.fromarray(rng.integers(0, 255, size=(6, 5, 4), dtype=np.uint8),
                    mode="RGBA").save(buf, format="PNG")
    out = _pil_decode_channels(buf.getvalue(), (6, 5), channels=4)
    assert out.shape == (6, 5, 4)
    with pytest.raises(ValueError, match="channel count"):
        _pil_decode_channels(buf.getvalue(), (6, 5), channels=2)


def test_bucket_size_respects_multiple_above_batch_size():
    from sparkdl_tpu.core.batching import bucket_size

    # ADVICE r2 footgun: n > batch_size escape must still honor `multiple`
    assert bucket_size(10, 8, multiple=4) == 12
    assert bucket_size(10, 8) == 10
    assert bucket_size(3, 8, multiple=8) == 8
