"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh (the environment has one real TPU
chip; mesh/sharding logic is validated on faked host devices exactly as
SURVEY.md §4 prescribes). These env vars MUST be set before jax is first
imported, hence they live at module import time in conftest.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# The Axon TPU environment registers its PJRT plugin from sitecustomize
# (which runs before conftest) and pins jax_platforms=axon in-config, so the
# env var alone is not enough — override the config too, before any backend
# is initialized.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Repo root on sys.path so `import sparkdl_tpu` works without install.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# Debug hardening (SURVEY.md §5.2): SPARKDL_DEBUG=1 runs the whole suite
# under jax_debug_nans + tracer-leak checking (slow: op-by-op; off by
# default). The NaN regression test enables it locally either way.
if os.environ.get("SPARKDL_DEBUG", "") not in ("", "0"):
    jax.config.update("jax_debug_nans", True)
    jax.config.update("jax_check_tracer_leaks", True)

# The suite's numeric contract is BIT-identity (chaos/durability/replay
# tests compare exact bytes), so the test default pins the inference
# path to float32 and the blind power-of-two ladder — at conftest IMPORT
# time, before any test module's EngineConfig snapshot runs, so every
# snapshot/restore fixture captures the pinned values. The library
# defaults stay bfloat16 + tuned (engine/dataframe.py); precision and
# planner tests opt back in explicitly.
from sparkdl_tpu.engine.dataframe import EngineConfig  # noqa: E402

EngineConfig.inference_precision = "float32"
EngineConfig.bucket_ladder = "pow2"


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture
def tiny_image_dir(tmp_path):
    """A directory of small deterministic JPEG+PNG fixtures."""
    from PIL import Image

    rng = np.random.default_rng(0)
    paths = []
    for i in range(4):
        arr = rng.integers(0, 255, size=(32 + 8 * i, 40, 3), dtype=np.uint8)
        p = tmp_path / f"img_{i}.jpg"
        Image.fromarray(arr).save(p, quality=95)
        paths.append(p)
    arr = rng.integers(0, 255, size=(24, 24, 3), dtype=np.uint8)
    p = tmp_path / "img_png.png"
    Image.fromarray(arr).save(p)
    paths.append(p)
    (tmp_path / "not_an_image.txt").write_text("hello")
    return tmp_path
