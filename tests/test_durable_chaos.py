"""Proof by chaos: kill -9 a live durable job mid-stream (decode pool
armed, prefetcher running) and resume it — bit-identical rows, zero
recomputed committed partitions, quarantine persisted, one telemetry
timeline spanning the crash, zero leaked shared-memory segments
(docs/RESILIENCE.md "Durable recovery")."""

import json
import os
import re
import signal
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.chaos

_CHILD = os.path.join(os.path.dirname(__file__), "_durable_chaos_child.py")


def _run_child(mode, work, expect_sig=None, timeout=300):
    proc = subprocess.run(
        [sys.executable, _CHILD, mode, str(work)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, timeout=timeout)
    out = proc.stdout.decode(errors="replace")
    if expect_sig is None:
        assert proc.returncode == 0, out[-3000:]
    else:
        assert proc.returncode == -expect_sig, (proc.returncode, out[-3000:])
    return out


def _journal_records(work):
    """partition -> record, from the single job dir's journal."""
    root = os.path.join(str(work), "durable")
    jobs = [d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d))]
    assert len(jobs) == 1, jobs  # one plan, one job id
    recs = {}
    with open(os.path.join(root, jobs[0], "journal.jsonl")) as f:
        for line in f.read().splitlines():
            rec = json.loads(line)["rec"]
            recs[rec["partition"]] = rec
    return recs


def _dead_owner_segments():
    if not os.path.isdir("/dev/shm"):
        return []
    out = []
    for name in os.listdir("/dev/shm"):
        m = re.match(r"^sdlshm_([0-9a-f]+)_", name)
        if m is None:
            continue
        try:
            os.kill(int(m.group(1), 16), 0)
        except ProcessLookupError:
            out.append(name)
        except PermissionError:
            pass  # alive, another uid
    return out


@pytest.fixture
def work(tmp_path):
    from PIL import Image

    rng = np.random.default_rng(3)
    d = tmp_path / "imgs"
    d.mkdir()
    for i in range(18):
        Image.fromarray(
            rng.integers(0, 255, (16, 16, 3), dtype=np.uint8)
        ).save(d / f"img_{i:02d}.png")
    return tmp_path


def test_kill9_mid_stream_resumes_bit_identical(work):
    # never-killed reference (own journal dir)
    _run_child("baseline", work)
    base = (work / "rows_baseline.arrow").read_bytes()
    assert base

    # kill -9 mid-stream: process_kill SIGKILLs self after the 3rd commit
    _run_child("killed", work, expect_sig=signal.SIGKILL)
    killed = _journal_records(work)
    assert 3 <= len(killed) < 6, sorted(killed)
    assert not (work / "rows_killed.arrow").exists()  # died mid-stream

    # resume: same plan, same journal dir
    _run_child("resumed", work)
    final = _journal_records(work)
    assert sorted(final) == [0, 1, 2, 3, 4, 5]

    # exactly-once: every record committed before the kill is served from
    # spill, byte-for-byte unchanged — zero recomputed committed partitions
    for i, rec in killed.items():
        assert final[i] == rec, f"partition {i} was recomputed"

    # quarantine verdict survives the crash: poisoned partition 0 is in
    # the final journal as a quarantined zero-row stand-in, not re-poisoned
    assert final[0]["quarantined"] is True

    # bit-identical output: resumed rows == never-killed rows
    assert (work / "rows_resumed.arrow").read_bytes() == base

    # pinned run id: ONE snapshot timeline + ONE run report span the crash
    run_id = (work / "durable" / "run_id").read_text().strip()
    snaps = sorted((work / "tel").glob("sparkdl_snapshots_*.jsonl"))
    reports = sorted((work / "tel").glob("sparkdl_run_report_*.json"))
    assert [p.name for p in snaps] == [f"sparkdl_snapshots_{run_id}.jsonl"]
    assert [p.name for p in reports] == [f"sparkdl_run_report_{run_id}.json"]
    assert snaps[0].read_text().strip()  # the shared timeline is non-empty
    assert json.loads(reports[0].read_text())["run_id"] == run_id

    # the dead run's shared-memory segments were reclaimed (resumed pool's
    # startup sweep): no segment names a dead owner pid
    assert _dead_owner_segments() == []
