"""Elastic capacity (ISSUE 16): live worker autoscaling, preemption-
aware graceful drain, and per-tenant fair queueing under overload.

The contract under test: the deficit-round-robin coalescer keeps a
flooded tenant inside its weighted share (a light tenant's queue-wait
p99 stays in budget while the flooder's tail absorbs the overload);
``autoscale_tick`` grows the live worker set on a hot windowed
queue-wait p99 and drains an idle worker when cold, with cooldown and
drain-grace enforcement; and a SIGTERM-with-warning (spot preemption)
becomes a graceful drain — the preempted worker finishes its in-flight
work, nothing is re-dispatched, and the run stays bit-identical with
zero re-execution of journal-committed partitions.
"""

import threading
import time

import numpy as np
import pyarrow as pa
import pytest

import jax
import jax.numpy as jnp

from sparkdl_tpu.cluster import router as cluster_router
from sparkdl_tpu.core import executor, health, slo, telemetry
from sparkdl_tpu.core.health import HealthMonitor
from sparkdl_tpu.core.model_function import ModelFunction, TensorSpec
from sparkdl_tpu.core.resilience import Fault, FaultInjector
from sparkdl_tpu.core.telemetry import Telemetry
from sparkdl_tpu.engine import DataFrame, EngineConfig

_ELEMENT = (6,)


@pytest.fixture(autouse=True)
def _fresh_state():
    saved = EngineConfig.snapshot()
    executor.reset()
    yield
    executor.reset()
    EngineConfig.restore(saved)
    cluster_router.shutdown()


def _frame(n=24, parts=4):
    return DataFrame.fromRows([{"x": i} for i in range(n)],
                              numPartitions=parts)


def _model(name, sleep_s=0.0):
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(_ELEMENT[0], 3)).astype(np.float32))

    def apply_fn(vs, x):
        if sleep_s:
            x = jax.pure_callback(
                lambda a: (time.sleep(sleep_s), a)[1],
                jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return jnp.tanh(x @ vs)

    return ModelFunction(apply_fn, w, TensorSpec((None,) + _ELEMENT,
                                                 "float32"), name=name)


def _rows(n, seed=1):
    return np.random.default_rng(seed).normal(
        size=(n,) + _ELEMENT).astype(np.float32)


def _wait_for(predicate, timeout_s=20.0, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


# -- deficit-round-robin: the scheduling kernel -------------------------------

class _LaneState:
    """The three fields ``_drr_release_locked`` reads, nothing else —
    the scheduling kernel is testable without a live device service."""

    def __init__(self, cap, weights=None):
        self.cap = cap
        self.tenant_weights = weights
        self.tenant_deficit = {}


def _req(tenant, rows=2):
    r = object.__new__(executor._Request)
    r.tenant = tenant
    r.rows = rows
    r.launched = False
    return r


def test_drr_interleaves_tenants_and_persists_deficit():
    """Unweighted DRR releases tenant heads alternately (a flooder that
    arrived first cannot monopolize the cap), throttles the tenant left
    queued, and banks its unspent credit for the next drain — while a
    tenant that drained dry forfeits its credit."""
    svc = executor.DeviceExecutor()
    state = _LaneState(cap=8)
    queues = {"flood": [_req("flood") for _ in range(6)],
              "paid": [_req("paid") for _ in range(2)]}
    batch, throttled = [], []
    total, overflow = svc._drr_release_locked(state, queues, batch, 0,
                                              throttled)
    assert overflow and total == 8
    # strict alternation up to the cap, despite flood's 6-deep FIFO
    assert [r.tenant for r in batch] == ["flood", "paid", "flood", "paid"]
    assert all(r.launched for r in batch)
    assert throttled == ["flood"]
    assert not queues["paid"] and len(queues["flood"]) == 4
    # fairness memory: flood banked the credit of the round the cap cut
    # short; paid (drained dry) banks nothing
    assert set(state.tenant_deficit) == {"flood"}


def test_drr_weights_scale_each_tenants_share():
    svc = executor.DeviceExecutor()
    state = _LaneState(cap=8, weights={"paid": 3})
    queues = {"flood": [_req("flood") for _ in range(6)],
              "paid": [_req("paid") for _ in range(6)]}
    batch, throttled = [], []
    total, overflow = svc._drr_release_locked(state, queues, batch, 0,
                                              throttled)
    assert overflow and total == 8
    by_tenant = {t: sum(1 for r in batch if r.tenant == t)
                 for t in ("flood", "paid")}
    assert by_tenant == {"flood": 1, "paid": 3}  # the 3x weight, exactly
    assert sorted(throttled) == ["flood", "paid"]


def test_single_tenant_lane_keeps_fifo_order_and_never_throttles():
    """One tenant in a lane takes the pre-fairness FIFO fast path: no
    deficit accounting, no TENANT_THROTTLED attribution."""
    mf = _model("fifo_fast_path")
    with HealthMonitor() as mon, Telemetry(out_dir=""):
        out = executor.execute(mf, _rows(4), batch_size=32,
                               tenant="solo")
        assert out.shape == (4, 3)
    assert mon.count(health.TENANT_THROTTLED) == 0


# -- per-tenant fairness under sustained overload -----------------------------

def test_flooded_tenant_absorbs_the_overload_not_the_light_one():
    """Chaos proof, executor half: tenant "flood" saturates the lane
    with 10 requests while "paid" (weighted 8x) submits 2. The paid
    requests overtake the flood backlog, both tenants get their own
    queue-wait series, the flooder is the one throttled, and paid's p99
    holds the SLO budget the flooder's tail blows through."""
    mf = _model("fairness_model", sleep_s=0.25)
    EngineConfig.coalesce_max_rows = 4      # small cap: DRR must arbitrate
    EngineConfig.executor_tenant_weights = {"paid": 8}
    budget_s = 2.0
    done = {}
    errors = []

    def submit(tenant, idx, seed):
        try:
            executor.execute(mf, _rows(2, seed=seed), batch_size=32,
                             tenant=tenant)
            done[(tenant, idx)] = time.monotonic()
        except Exception as e:  # pragma: no cover - surfaced below
            errors.append(e)

    with HealthMonitor() as mon, Telemetry(out_dir="") as tel:
        threads = [threading.Thread(target=submit,
                                    args=("flood", i, i))
                   for i in range(10)]
        for t in threads:
            t.start()
        time.sleep(0.05)  # the flood is queued; now the light tenant
        paid = [threading.Thread(target=submit, args=("paid", i, 100 + i))
                for i in range(2)]
        for t in paid:
            t.start()
        for t in threads + paid:
            t.join(timeout=60)
        assert not errors, errors
        snap = tel.metrics.window_snapshot()
    assert len(done) == 12

    # the light tenant overtook the backlog: both paid requests finished
    # before the flood's tail
    flood_tail = max(ts for (t, _i), ts in done.items() if t == "flood")
    assert all(ts < flood_tail
               for (t, _i), ts in done.items() if t == "paid")

    # the flooder was throttled, and more often than anyone else (paid
    # may brush the cap in an early round; the flood lives behind it)
    throttle_events = mon.events(health.TENANT_THROTTLED)
    assert throttle_events
    by_tenant = {}
    for e in throttle_events:
        by_tenant[e["tenant"]] = by_tenant.get(e["tenant"], 0) + 1
    assert "flood" in by_tenant
    assert by_tenant["flood"] == max(by_tenant.values())

    # per-tenant series exist (per-tenant NAMES), and the SLO verdict
    # lands the right way around: paid inside budget, flood's tail out
    paid_hist = snap["histograms"].get(
        telemetry.tenant_queue_wait_metric("paid"))
    flood_hist = snap["histograms"].get(
        telemetry.tenant_queue_wait_metric("flood"))
    assert paid_hist and paid_hist["count"] == 2
    # a solo request under no contention launches inline on the caller's
    # thread and skips the coalescer (and its per-tenant observe) — the
    # first and/or last flood request may legally be missing here
    assert flood_hist and 8 <= flood_hist["count"] <= 10
    assert paid_hist["max"] < flood_hist["max"]
    (rule,) = slo.tenant_queue_wait_rules({"paid": budget_s})
    assert rule.metric == telemetry.tenant_queue_wait_metric("paid")
    assert paid_hist["p99"] is not None
    assert paid_hist["p99"] <= rule.threshold
    assert flood_hist["max"] > paid_hist["p99"]


# -- the autoscaler ----------------------------------------------------------

def _manual_router(workers):
    """A router with the autoscaler ARMED but its background thread
    stopped — ticks are driven by hand, deterministically."""
    EngineConfig.cluster_autoscale = True
    router = cluster_router.ClusterRouter(workers=workers)
    router._autoscale_stop.set()
    if router._autoscale_thread is not None:
        router._autoscale_thread.join(timeout=10)
    return router


def _live(router):
    with router._lock:
        return [w for w in router._workers
                if not w.lost and not w.finished and not w.draining]


def test_autoscale_scales_up_on_hot_p99_and_drains_back_when_cold():
    EngineConfig.cluster_min_workers = 1
    EngineConfig.cluster_max_workers = 2
    EngineConfig.autoscale_cooldown_s = 0.0
    EngineConfig.autoscale_queue_wait_high_s = 0.5
    EngineConfig.autoscale_queue_wait_low_s = 0.05
    with HealthMonitor() as mon:
        router = _manual_router(workers=1)
        try:
            with Telemetry(out_dir="") as tel:
                for _ in range(8):
                    telemetry.observe(telemetry.M_QUEUE_WAIT_S, 1.0)
                assert router.autoscale_tick() == "up"
                assert len(_live(router)) == 2
                assert mon.count(health.CLUSTER_SCALE_UP) == 1
                # the live-worker gauge tracked the spawn
                assert tel.metrics.snapshot()["gauges"][
                    telemetry.M_CLUSTER_WORKERS] == 2
                # still hot, but already at cluster_max_workers: no-op
                assert router.autoscale_tick() is None
                # cooldown gates even a hot signal
                EngineConfig.autoscale_cooldown_s = 3600.0
                EngineConfig.cluster_max_workers = 3
                assert router.autoscale_tick() is None
                assert len(_live(router)) == 2
                EngineConfig.autoscale_cooldown_s = 0.0
                EngineConfig.cluster_max_workers = 2

            # scope closed: no windowed p99 at all reads as cold
            assert router.autoscale_tick() == "down"
            assert mon.count(health.CLUSTER_SCALE_DOWN) == 1
            # the newest worker drains (idle: the pill goes out at once)
            _wait_for(lambda:
                      mon.count(health.CLUSTER_WORKER_DRAINED) == 1,
                      what="idle worker to drain")
            assert len(_live(router)) == 1
            # at the floor: cold ticks are no-ops now
            assert router.autoscale_tick() is None
        finally:
            router.close()
        events = [e["action"] for e in router.autoscale_events]
        assert events == ["spawn", "draining", "drained"]
        auto = router.cluster_report["autoscale"]
        assert auto["scale_ups"] == 1
        assert auto["scale_downs"] == 1
        assert auto["drained"] == 1
        assert mon.count(health.CLUSTER_WORKER_DRAINING) == 1


def test_drain_grace_tears_down_a_stuck_worker_and_redispatches():
    """DrainTimeout: a draining worker whose in-flight work outlives the
    grace is torn down hard — its tasks take the ordinary lost-worker
    re-dispatch path, so the job still completes."""
    router = _manual_router(workers=2)
    try:
        def slow(b):
            import time as _t
            _t.sleep(8)
            return b

        token = router._ops_payload([slow])
        batch = pa.record_batch([pa.array([1, 2, 3])], names=["x"])
        with HealthMonitor() as mon:
            task = router._submit(0, batch, token)
            with router._lock:
                victim = next(w for w in router._workers
                              if w.wid == task.worker)
            router._begin_drain(victim, reason="scale_down")
            assert victim.draining and not victim.pilled  # work in flight
            # a busy drain inside the grace is left alone
            assert router.autoscale_tick() is None
            assert victim.proc.is_alive()
            # ...but past the grace it is torn down hard
            with router._lock:
                victim.drain_started -= (
                    cluster_router._DRAIN_GRACE_S + 1.0)
            router.autoscale_tick()
            got = router._await(task, None)  # re-dispatched, completes
            assert got.equals(batch)
        assert any(e["action"] == "drain_timeout"
                   and e.get("error") == "DrainTimeout"
                   for e in router.autoscale_events)
        assert mon.count(health.CLUSTER_WORKER_LOST) == 1
        assert mon.count(health.CLUSTER_REDISPATCH) >= 1
        assert mon.count(health.CLUSTER_WORKER_DRAINED) == 0
    finally:
        router.close()


def test_dispatch_excludes_draining_workers():
    """A draining worker takes no NEW work; with every worker draining,
    dispatch fails WorkerDraining (RETRYABLE — the supervisor's retry
    re-dispatches once capacity returns)."""
    from sparkdl_tpu.core import resilience

    EngineConfig.cluster_autoscale = False
    router = cluster_router.ClusterRouter(workers=2)
    try:
        token = router._ops_payload([lambda b: b])
        batch = pa.record_batch([pa.array([1, 2, 3])], names=["x"])
        with router._lock:
            a, b = router._workers
        router._begin_drain(a, reason="scale_down")
        t = router._submit(0, batch, token)
        assert t.worker == b.wid  # the draining worker got nothing
        assert router._await(t, None).equals(batch)
        router._begin_drain(b, reason="scale_down")
        with pytest.raises(resilience.WorkerDraining) as ei:
            router._submit(1, batch, token)
        assert resilience.classify(ei.value) == resilience.RETRYABLE
    finally:
        router.close()


# -- preemption: the chaos proof ---------------------------------------------

def test_preemption_drains_gracefully_with_zero_recompute(tmp_path):
    """Chaos proof, cluster half: a SIGTERM-with-warning lands on a
    worker mid-run (armed ``cluster_worker_preempt`` marker). The worker
    finishes the very task that carried the warning, notifies the
    router, drains, and exits clean; a replacement spawns to hold the
    floor. No ClusterWorkerLost, no re-dispatch, every journal-committed
    partition executes exactly once, and the output is bit-identical to
    an undisturbed run."""
    trace = tmp_path / "executions.log"

    def build():
        def op(batch):
            with open(trace, "a") as f:  # worker-side side effect
                f.write(f"{batch.column('x')[0].as_py()}\n")
            return pa.compute.add(batch.column("x"), 1)

        return _frame(36, 6).withColumnBatch("y", op,
                                             outputType=pa.int64())

    want = build().collect()          # clean in-process run
    trace.write_text("")

    EngineConfig.durable_dir = str(tmp_path / "durable")
    EngineConfig.cluster_workers = 2
    inj = FaultInjector.seeded(0, cluster_worker_preempt=Fault(times=1,
                                                               after=2))
    try:
        with inj, HealthMonitor("preempt-chaos") as mon:
            got = build().collect()
            # the preempted worker's clean exit (snapshot + EOF) races
            # the end of collect(); hold the scope until it lands
            _wait_for(lambda:
                      mon.count(health.CLUSTER_WORKER_DRAINED) == 1,
                      what="preempted worker to finish draining")
    finally:
        cluster_router.shutdown()

    assert inj.fired == {"cluster_worker_preempt": 1}
    assert got == want                                   # bit-identical
    assert len(trace.read_text().splitlines()) == 6      # zero recompute
    # the drain was graceful: a preemption is NOT a worker loss
    assert mon.count(health.CLUSTER_PREEMPTION_NOTICE) >= 1
    assert mon.count(health.CLUSTER_WORKER_LOST) == 0
    assert mon.count(health.CLUSTER_REDISPATCH) == 0
    assert mon.count(health.CLUSTER_WORKER_DRAINING) == 1
    # a replacement spawned to hold the 2-worker floor
    assert mon.count(health.CLUSTER_WORKER_STARTED) == 3

    # merged report: all three workers shipped finals (the drained one
    # shipped its snapshot BEFORE exiting), rows fully accounted for
    rep = cluster_router.last_cluster_report()
    assert rep["worker_count"] == 3
    assert sum(rep["tasks_per_worker"].values()) == 6
    assert rep["health_consistent"] is True
    auto = rep["autoscale"]
    assert auto["drained"] == 1
    assert [e["action"] for e in auto["events"]][:2] == ["draining",
                                                         "spawn"]
    assert any(e.get("reason") == "replace_preempted"
               for e in auto["events"])
