"""Cluster inference plane: partition router, worker processes, merged
observability (docs/DISTRIBUTED.md "Cluster inference").

The contract under test is ISSUE 14's acceptance list: cluster_workers=0
leaves every path byte-identical and never imports this package; a
2-worker run is bit-identical to the in-process run for materialize AND
stream; worker death re-dispatches precisely and stays bit-identical;
remote errors keep their resilience classification (retryable retried,
fatal not); the merged report's health counters equal the sum of the
per-worker monitors; and the router composes with the durable journal
(committed partitions are zero-recompute across a cluster run).
"""

import multiprocessing
import os
import subprocess
import sys

import numpy as np
import pyarrow as pa
import pytest

from sparkdl_tpu.cluster import router as cluster_router
from sparkdl_tpu.core import health, resilience, telemetry
from sparkdl_tpu.core.health import HealthMonitor
from sparkdl_tpu.core.telemetry import Telemetry
from sparkdl_tpu.core.resilience import Fault, FaultInjector
from sparkdl_tpu.engine import DataFrame, EngineConfig, TaskFailure

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@pytest.fixture(autouse=True)
def _restore_engine_config():
    saved = EngineConfig.snapshot()
    yield
    EngineConfig.restore(saved)
    cluster_router.shutdown()  # idempotent; no test leaks a live router


def _frame(n=24, parts=4):
    return DataFrame.fromRows([{"x": i} for i in range(n)],
                              numPartitions=parts)


def _featurized(n=24, parts=4):
    """A plan whose op chain crosses the pickle boundary with a captured
    jax array AND records a worker-side health event per partition — the
    two things the merged report has to account for."""
    import jax.numpy as jnp

    rng = np.random.default_rng(5)
    w = jnp.asarray(rng.normal(size=(1, 3)).astype(np.float32))

    def op(batch):
        health.record("cluster_probe")
        x = np.asarray(batch.column("x"), dtype=np.float32).reshape(-1, 1)
        y = np.asarray(jnp.tanh(x @ w), dtype=np.float32)
        return pa.array(y.sum(axis=1).astype("float64"))

    return _frame(n, parts).withColumnBatch("y", op,
                                            outputType=pa.float64())


def _assert_no_live_workers(router):
    assert all(not w.proc.is_alive() for w in router._workers)
    assert router._pending == {}


# -- the gate ----------------------------------------------------------------

def test_workers_zero_never_imports_cluster():
    """The 0-default must keep the module un-imported, not just unused —
    pinned in a subprocess because this test session itself imports it."""
    script = (
        "import sys\n"
        "import pyarrow as pa\n"
        "from sparkdl_tpu.engine import DataFrame, EngineConfig\n"
        "assert EngineConfig.cluster_workers == 0\n"
        "df = DataFrame.fromRows([{'x': i} for i in range(8)],"
        " numPartitions=2)\n"
        "out = df.withColumnBatch('y',"
        " lambda b: pa.compute.add(b.column('x'), 1),"
        " outputType=pa.int64()).collect()\n"
        "assert [r['y'] for r in out] == [i + 1 for i in range(8)]\n"
        "rogue = sorted(m for m in sys.modules"
        " if m.startswith('sparkdl_tpu.cluster'))\n"
        "assert not rogue, rogue\n"
        "print('CLEAN')\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=_REPO + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, timeout=240)
    out = proc.stdout.decode(errors="replace")
    assert proc.returncode == 0, out[-3000:]
    assert "CLEAN" in out


def test_workers_zero_is_inline_and_routerless():
    assert EngineConfig.cluster_workers == 0
    assert cluster_router.maybe_router() is None
    assert cluster_router._router is None
    got = _frame(8, 2).collect()
    assert [r["x"] for r in got] == list(range(8))
    assert cluster_router._router is None  # the run armed nothing


def test_maybe_router_validates_knobs_at_the_read_site():
    EngineConfig.cluster_workers = -1
    with pytest.raises(ValueError, match="cluster_workers"):
        cluster_router.maybe_router()
    EngineConfig.cluster_workers = 2
    EngineConfig.cluster_inflight_partitions = 0
    with pytest.raises(ValueError, match="cluster_inflight_partitions"):
        cluster_router.maybe_router()


# -- parity + merged observability -------------------------------------------

def test_cluster_bit_identical_and_report_proves_health_sums():
    want_rows = _featurized().collect()
    want_stream = [b for b in _featurized().streamPartitions()]

    EngineConfig.cluster_workers = 2
    with HealthMonitor("cluster-parity") as mon, \
            Telemetry(name="cluster-parity", out_dir="") as tel:
        try:
            got_rows = _featurized().collect()
            got_stream = [b for b in _featurized().streamPartitions()]
        finally:
            # shutdown INSIDE the scope: close is the moment the finals
            # merge, and the merged RunReport needs the active scope
            cluster_router.shutdown()

    assert got_rows == want_rows  # bit-identical materialize
    assert len(got_stream) == len(want_stream) == 4
    for g, w in zip(got_stream, want_stream):
        assert g.equals(w)  # bit-identical stream, original order

    rep = cluster_router.last_cluster_report()
    assert rep is not None and rep["worker_count"] == 2
    # every partition ran on SOME worker, rows fully accounted for
    assert sum(rep["rows_per_worker"].values()) == 2 * 24
    assert sum(rep["tasks_per_worker"].values()) == 2 * 4
    # the acceptance invariant: merged health counters == the sum of the
    # per-worker monitors, re-derived here independently of aggregate.py
    manual = {}
    for snap in rep["workers"].values():
        assert snap["run_id"] == tel.run_id  # pinned to the coordinator
        for name, value in snap["health"]["counters"].items():
            manual[name] = manual.get(name, 0) + value
    assert rep["health"]["counters"] == manual
    assert manual["cluster_probe"] == 2 * 4  # one per partition per run
    assert rep["health_consistent"] is True

    # the merged RunReport carries the cluster section + the run id
    run_report = cluster_router.last_run_report()
    assert run_report is not None
    assert run_report["run_id"] == tel.run_id
    assert run_report["cluster"]["worker_count"] == 2
    # coordinator-side lifecycle events stayed coordinator-side
    assert mon.count(health.CLUSTER_WORKER_STARTED) == 2
    assert mon.count(health.CLUSTER_WORKER_LOST) == 0


# -- resilience semantics across the process boundary ------------------------

def test_remote_errors_keep_their_classification(tmp_path):
    marker = tmp_path / "fired-once"

    def build(kind):
        def op(batch):
            lo = batch.column("x")[0].as_py()
            if kind == "retryable" and lo == 0 and not marker.exists():
                marker.write_text("x")  # next attempt succeeds
                raise resilience.WorkerFault(
                    "injected worker-side retryable loss")
            if kind == "fatal" and lo == 0:
                raise ValueError("deliberately malformed partition")
            return pa.compute.add(batch.column("x"), 1)

        return _frame(8, 2).withColumnBatch("y", op,
                                            outputType=pa.int64())

    EngineConfig.cluster_workers = 2
    try:
        with HealthMonitor("cluster-retry") as mon:
            got = build("retryable").collect()
        assert [r["y"] for r in got] == [i + 1 for i in range(8)]
        assert marker.exists()
        assert mon.count(health.TASK_RETRIED) == 1
        assert mon.count(health.TASK_FAILED) == 0

        with HealthMonitor("cluster-fatal") as mon:
            with pytest.raises(TaskFailure, match="fatal"):
                build("fatal").collect()
        assert mon.count(health.TASK_RETRIED) == 0  # fatal: never retried
        assert mon.count(health.TASK_FAILED) == 1
    finally:
        cluster_router.shutdown()


def test_worker_death_redispatches_precisely_and_stays_bit_identical():
    want = _featurized(36, 6).collect()

    def _segments():
        if not os.path.isdir("/dev/shm"):
            return set()
        return {n for n in os.listdir("/dev/shm") if n.startswith("psm_")}

    before = _segments()
    EngineConfig.cluster_workers = 2
    inj = FaultInjector.seeded(0, cluster_worker_kill=Fault(times=1,
                                                            after=2))
    try:
        with inj, HealthMonitor("cluster-chaos") as mon:
            got = _featurized(36, 6).collect()
    finally:
        cluster_router.shutdown()

    assert inj.fired == {"cluster_worker_kill": 1}
    assert got == want  # bit-identical THROUGH the worker loss
    assert mon.count(health.CLUSTER_WORKER_STARTED) == 2
    assert mon.count(health.CLUSTER_WORKER_LOST) == 1  # one death, one event
    # the killed worker held at least the partition whose dispatch armed
    # the kill; each moved partition is one redispatch event
    assert mon.count(health.CLUSTER_REDISPATCH) >= 1

    router = cluster_router._last_router
    _assert_no_live_workers(router)
    assert _segments() - before == set()  # no leaked shm segments
    # the survivor's final snapshot still merged (the dead worker cannot
    # ship one — worker_count counts snapshots, not spawns); the dead
    # worker's pre-death completions died with it, so the survivor
    # accounts for everything it ran: at least the re-dispatched work
    rep = cluster_router.last_cluster_report()
    assert rep["worker_count"] == 1
    assert 0 < sum(rep["rows_per_worker"].values()) <= 36


def test_no_survivors_fails_retryable():
    router = cluster_router.ClusterRouter(workers=1)
    try:
        ops = [lambda b: b]
        token = router._ops_payload(ops)
        batch = pa.record_batch([pa.array([1, 2, 3])], names=["x"])
        with FaultInjector.seeded(0, cluster_worker_kill=1):
            task = router._submit(0, batch, token)
            with pytest.raises(resilience.ClusterWorkerLost) as ei:
                router._await(task, None)
        # the supervisor's retry loop sees a RETRYABLE kind — workers
        # coming back (or a redundant cluster) makes the retry land
        assert resilience.classify(ei.value) == resilience.RETRYABLE
        # with zero survivors a fresh dispatch fails the same way
        with pytest.raises(resilience.ClusterWorkerLost):
            router._submit(1, batch, token)
    finally:
        router.close()
    _assert_no_live_workers(router)


def test_hedge_antiaffinity_and_load_aware_spread():
    router = cluster_router.ClusterRouter(workers=2)
    try:
        # a slow op keeps submitted tasks in-flight long enough that the
        # worker-selection assertions below are deterministic, not a
        # race against the worker's round-trip
        def slow(b):
            import time
            time.sleep(0.5)
            return b

        token = router._ops_payload([slow])
        batch = pa.record_batch([pa.array([1, 2, 3])], names=["x"])
        # two concurrent attempts of the SAME partition (a hedge) must
        # land on different workers
        t1 = router._submit(7, batch, token)
        t2 = router._submit(7, batch, token)
        assert t1.worker != t2.worker
        assert router._await(t1, None).equals(batch)
        assert router._await(t2, None).equals(batch)
        # load-aware spread: with t3 outstanding on one worker, the next
        # distinct partition goes to the idle one
        t3 = router._submit(8, batch, token)
        t4 = router._submit(9, batch, token)
        assert t3.worker != t4.worker
        router._await(t3, None)
        router._await(t4, None)
    finally:
        router.close()
    router.close()  # idempotent
    _assert_no_live_workers(router)
    assert router.cluster_report["worker_count"] == 2


# -- lifecycle + composition -------------------------------------------------

def test_maybe_router_lifecycle_follows_the_knobs():
    EngineConfig.cluster_workers = 1
    try:
        r1 = cluster_router.maybe_router()
        assert r1 is not None and r1.workers == 1
        assert cluster_router.maybe_router() is r1  # cached while knobs hold
        EngineConfig.cluster_inflight_partitions = 3
        r2 = cluster_router.maybe_router()
        assert r2 is not r1 and r2.inflight == 3
        assert r1.closed  # reconfigure closed (and merged) the old router
    finally:
        cluster_router.shutdown()
    assert r2.closed
    assert cluster_router._router is None
    assert cluster_router.last_cluster_report() is not None
    _assert_no_live_workers(r2)
    # no stray cluster children anywhere after shutdown
    names = [p.name for p in multiprocessing.active_children()]
    assert not any(n.startswith("sparkdl-cluster") for n in names), names


def test_durable_journal_composes_with_cluster(tmp_path):
    """PR 11 x PR 14: the journal wraps OUTSIDE the router, so a second
    cluster run of the same plan restores every partition from spill —
    zero worker-side re-execution."""
    EngineConfig.durable_dir = str(tmp_path / "durable")
    EngineConfig.cluster_workers = 2
    trace = tmp_path / "executions.log"

    def build():
        def op(batch):
            with open(trace, "a") as f:  # worker-side side effect
                f.write(f"{batch.column('x')[0].as_py()}\n")
            return pa.compute.add(batch.column("x"), 1)

        return _frame(12, 4).withColumnBatch("y", op,
                                             outputType=pa.int64())

    try:
        want = build().collect()
        assert len(trace.read_text().splitlines()) == 4
        with HealthMonitor("cluster-durable") as mon:
            got = build().collect()  # fresh frame, same plan -> same job
    finally:
        cluster_router.shutdown()

    assert got == want
    assert len(trace.read_text().splitlines()) == 4  # zero recompute
    assert len(mon.events(health.DURABLE_PARTITION_RESTORED)) == 4
