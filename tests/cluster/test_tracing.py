"""Cross-process distributed tracing (ISSUE 15 tentpole): a cluster
featurize (workers=2) with the decode pool armed in the coordinator
produces ONE merged Chrome trace — worker task spans and in-worker
decode-chunk spans parent transitively under the coordinator's
``sparkdl.run`` — proven by walking parent links, not by name matching
alone. Plus: per-worker span-ring accounting in the merged report, the
SIGKILL chaos leg with tracing armed (exactly one span-ring-lost entry,
outputs still bit-identical), and the off-path guarantee (no telemetry
scope -> no rings shipped, no trace section, nothing new in reports).
"""

import io
import os

import numpy as np
import pyarrow as pa
import pytest
from PIL import Image

from sparkdl_tpu.cluster import router as cluster_router
from sparkdl_tpu.core import decode_pool, health, telemetry
from sparkdl_tpu.core.decode_pool import DecodePool
from sparkdl_tpu.core.health import HealthMonitor
from sparkdl_tpu.core.resilience import Fault, FaultInjector
from sparkdl_tpu.core.telemetry import Telemetry
from sparkdl_tpu.engine import DataFrame, EngineConfig

# clock-handshake slack when comparing adopted remote timestamps with
# coordinator-side span bounds (the offset estimate is RTT/2-accurate;
# 50 ms is orders of magnitude above a local pipe round-trip)
_CLOCK_SLACK_NS = 50_000_000


@pytest.fixture(autouse=True)
def _restore_engine_config():
    saved = EngineConfig.snapshot()
    yield
    EngineConfig.restore(saved)
    cluster_router.shutdown()
    decode_pool.shutdown()


def _frame(n=24, parts=4):
    return DataFrame.fromRows([{"x": i} for i in range(n)],
                              numPartitions=parts)


def _featurized(n=24, parts=4):
    import jax.numpy as jnp

    rng = np.random.default_rng(5)
    w = jnp.asarray(rng.normal(size=(1, 3)).astype(np.float32))

    def op(batch):
        health.record("cluster_probe")
        x = np.asarray(batch.column("x"), dtype=np.float32).reshape(-1, 1)
        y = np.asarray(jnp.tanh(x @ w), dtype=np.float32)
        return pa.array(y.sum(axis=1).astype("float64"))

    return _frame(n, parts).withColumnBatch("y", op,
                                            outputType=pa.float64())


def _blobs(n=8):
    rng = np.random.default_rng(0)
    out = []
    for i in range(n):
        buf = io.BytesIO()
        Image.fromarray(rng.integers(0, 255, (8 + 8 * (i % 3), 16, 3),
                                     dtype=np.uint8)
                        ).save(buf, format="JPEG", quality=90)
        out.append(buf.getvalue())
    return out


def _walk(by_id, span):
    """Follow parent links up to the root, asserting every link resolves
    inside the merged ring (a dangling parent = a span that shipped but
    whose parent didn't) and that there are no cycles. Returns the chain
    root-last."""
    chain = [span]
    seen = {span["span_id"]}
    cur = span
    while cur["parent_id"] is not None:
        pid = cur["parent_id"]
        assert pid in by_id, (
            f"dangling parent link {pid:#x} from {cur['name']!r}")
        cur = by_id[pid]
        assert cur["span_id"] not in seen, "parent-link cycle"
        seen.add(cur["span_id"])
        chain.append(cur)
    return chain


# -- the acceptance walk -----------------------------------------------------

def test_cluster_trace_merges_under_one_run_root():
    """Workers=2 cluster featurize + a coordinator-side pooled decode,
    one telemetry scope: every remote span (cluster task, decode chunk)
    walks parent links to the SAME ``sparkdl.run`` root."""
    EngineConfig.cluster_workers = 2
    with Telemetry(name="trace-merge", out_dir="") as tel:
        try:
            _featurized().collect()
            with DecodePool(workers=2) as pool:
                got = pool.decode(_blobs(8), target_size=(8, 8),
                                  channels=3)
            assert all(a is not None for a in got)
        finally:
            # inside the scope: close() is the adoption moment and the
            # merged RunReport needs the active scope
            cluster_router.shutdown()

    # assertions AFTER scope exit: the run root records at __exit__
    spans = tel.tracer.spans()
    by_id = {s["span_id"]: s for s in spans}
    own_pid = os.getpid()
    assert {s["trace_id"] for s in spans} == {tel.run_id}

    tasks = [s for s in spans
             if s["name"] == telemetry.SPAN_CLUSTER_TASK]
    assert len(tasks) == 4  # one adopted worker span per partition
    for s in tasks:
        assert s["pid"] != own_pid  # measured in a worker process
        chain = _walk(by_id, s)
        names = [c["name"] for c in chain]
        assert names[1] == telemetry.SPAN_CLUSTER_DISPATCH
        assert names[-1] == telemetry.SPAN_RUN
        assert chain[-1]["parent_id"] is None
        # the handshake made the timelines comparable: the coordinator's
        # dispatch round-trip encloses the worker-side task span
        disp = chain[1]
        assert "pid" not in disp  # coordinator-local span
        assert s["start_ns"] >= disp["start_ns"] - _CLOCK_SLACK_NS
        assert s["end_ns"] <= disp["end_ns"] + _CLOCK_SLACK_NS

    chunks = [s for s in spans
              if s["name"] == telemetry.SPAN_DECODE_CHUNK]
    assert chunks  # the pool fanned out at least one chunk
    for s in chunks:
        assert s["pid"] != own_pid
        chain = _walk(by_id, s)
        names = [c["name"] for c in chain]
        assert names[1] == telemetry.SPAN_DECODE_POOL
        assert names[-1] == telemetry.SPAN_RUN

    summ = tel.tracer.summary()
    assert summ["remote_adopted"] >= len(tasks) + len(chunks)
    assert summ["remote_rejected"] == 0

    # per-worker span-ring accounting in the merged cluster section
    rep = cluster_router.last_cluster_report()
    trace = rep["trace"]
    assert trace["span_rings_lost"] == []
    assert set(trace["workers"]) == set(rep["workers"])
    for acct in trace["workers"].values():
        assert acct["shipped"] >= 1
        assert acct["dropped"] == 0
    run_report = cluster_router.last_run_report()
    assert run_report is not None
    assert run_report["cluster"]["trace"] == trace

    # ONE Chrome document with labeled process groups per remote process
    doc = tel.tracer.chrome_trace()
    labels = {e["args"]["name"] for e in doc["traceEvents"]
              if e["ph"] == "M" and e["name"] == "process_name"}
    assert "coordinator" in labels
    assert any(l.startswith("sparkdl-cluster-") for l in labels)
    assert any(l.startswith("decode-") for l in labels)
    pids = {e["pid"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert len(pids) >= 3  # coordinator + >=1 cluster + >=1 decode pid


# -- the off path ------------------------------------------------------------

def test_no_scope_ships_no_rings_and_reports_stay_shaped():
    """Without a telemetry scope nothing about tracing leaks into the
    cluster protocol or the merged report: no span_ring in snapshots, no
    ``trace`` section, no merged RunReport at all."""
    EngineConfig.cluster_workers = 2
    try:
        got = _featurized().collect()
    finally:
        cluster_router.shutdown()
    assert len(got) == 24

    rep = cluster_router.last_cluster_report()
    assert rep is not None and rep["worker_count"] == 2
    assert "trace" not in rep
    for snap in rep["workers"].values():
        assert "span_ring" not in snap
    assert cluster_router.last_run_report() is None


# -- chaos: SIGKILL with tracing armed ---------------------------------------

def test_worker_kill_keeps_merged_trace_and_accounts_the_lost_ring():
    """One worker SIGKILLed mid-stream with tracing armed: outputs stay
    bit-identical, the merged trace still builds with correct parenting
    from the survivor, and the dead worker shows up as EXACTLY ONE
    span-ring-lost accounting entry (its spans died with it — the report
    says so instead of pretending full coverage)."""
    want = _featurized(36, 6).collect()

    EngineConfig.cluster_workers = 2
    inj = FaultInjector.seeded(0, cluster_worker_kill=Fault(times=1,
                                                            after=2))
    with HealthMonitor("trace-chaos") as mon, \
            Telemetry(name="trace-chaos", out_dir="") as tel:
        try:
            with inj:
                got = _featurized(36, 6).collect()
        finally:
            cluster_router.shutdown()

    assert inj.fired == {"cluster_worker_kill": 1}
    assert got == want  # bit-identical THROUGH the loss, tracing armed
    assert mon.count(health.CLUSTER_WORKER_LOST) == 1

    rep = cluster_router.last_cluster_report()
    assert rep["worker_count"] == 1  # snapshots, not spawns
    trace = rep["trace"]
    assert len(trace["span_rings_lost"]) == 1
    (survivor,) = trace["workers"]
    assert survivor not in trace["span_rings_lost"]
    assert trace["workers"][survivor]["shipped"] >= 1

    # the survivor's spans still parent correctly under the run root
    spans = tel.tracer.spans()
    by_id = {s["span_id"]: s for s in spans}
    tasks = [s for s in spans
             if s["name"] == telemetry.SPAN_CLUSTER_TASK]
    assert tasks  # at least the re-dispatched partitions ran somewhere
    for s in tasks:
        chain = _walk(by_id, s)
        assert chain[-1]["name"] == telemetry.SPAN_RUN
    # and the merged Chrome doc still builds as one multi-process trace
    doc = tel.tracer.chrome_trace()
    pids = {e["pid"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert len(pids) >= 2
