"""Live cluster metrics federation (ISSUE 19 tentpole): workers ship
bounded windowed-metrics frames at the federation cadence, the
coordinator folds them into ONE :class:`ClusterMetricsView`, the
federated SLO watchdog evaluates cluster-level rules against the
merged view, and a breach (or a worker loss) triggers a flight-recorder
postmortem bundle — written atomically BEFORE the run ends.

Covers: the frame build/fold unit surface (counters summed, gauge
envelopes merged, histogram buckets summed so a cluster p99 is a real
merged percentile), clock-skew window alignment (±2-slot worker
offsets rebase onto the coordinator clock with no double-count and no
gap), staleness/mark-dead accounting, the AGGREGATE-breach chaos leg
(no single worker breaches the queue-wait SLO but the cluster merged
p99 does — the watchdog fires live, mid-run), the SIGKILL leg with
federation armed (outputs bit-identical, the dead worker ages out and
its last shipped frame lands in the postmortem bundle), and the
off-path guarantee (federation unarmed -> no frames, no ``federation``
report section, no postmortem dirs, exporter artifacts unchanged).
"""

import glob
import json
import os
import time
import types

import numpy as np
import pyarrow as pa
import pytest

from sparkdl_tpu.cluster import aggregate
from sparkdl_tpu.cluster import router as cluster_router
from sparkdl_tpu.core import decode_pool, health, slo, telemetry
from sparkdl_tpu.core.health import HealthMonitor
from sparkdl_tpu.core.resilience import Fault, FaultInjector
from sparkdl_tpu.core.telemetry import Telemetry
from sparkdl_tpu.engine import DataFrame, EngineConfig

# the synthetic registries below: 60 s window over 12 ring slots
_SPAN_S = 5.0


@pytest.fixture(autouse=True)
def _restore_engine_config():
    saved = EngineConfig.snapshot()
    yield
    EngineConfig.restore(saved)
    cluster_router.shutdown()
    decode_pool.shutdown()


# -- synthetic-frame helpers (no cluster spawned) -----------------------------

def _registry(exemplar_k=0):
    return telemetry.MetricsRegistry(window_s=60.0, window_buckets=12,
                                     exemplar_k=exemplar_k)


def _frame(reg, worker, wid, seq=1, offset_ns=0):
    """Build a federation frame through the REAL worker-side builder."""
    shim = types.SimpleNamespace(metrics=reg)
    frame = aggregate.build_frame(worker, wid, seq, shim,
                                  clock_offset_ns=offset_ns)
    assert frame is not None
    return frame


def _fixed_clock(monkeypatch, t):
    monkeypatch.setattr(telemetry, "_monotonic", lambda: t)


# -- the fold: counters summed, buckets merged, real cluster p99 --------------

def test_fold_sums_counters_and_merges_histogram_buckets(monkeypatch):
    now = 1002.5  # mid-slot on the 5 s ladder
    _fixed_clock(monkeypatch, now)

    reg_a, reg_b = _registry(), _registry()
    for reg, n in ((reg_a, 3), (reg_b, 5)):
        for _ in range(n):
            reg.counter(telemetry.M_ENGINE_ROWS_OUT).inc()
    reg_a.gauge(telemetry.M_EXECUTOR_QUEUE_DEPTH).set(2.0)
    reg_b.gauge(telemetry.M_EXECUTOR_QUEUE_DEPTH).set(7.0)
    for v in (0.2, 0.2, 0.4):
        reg_a.histogram(telemetry.M_QUEUE_WAIT_S).observe(v)
    for v in (0.2, 0.8):
        reg_b.histogram(telemetry.M_QUEUE_WAIT_S).observe(v)

    view = aggregate.ClusterMetricsView(cadence_s=0.25)
    view.ingest(_frame(reg_a, "sparkdl-cluster-0", 0), now=now)
    view.ingest(_frame(reg_b, "sparkdl-cluster-1", 1), now=now)

    snap = view.window_snapshot(60.0, now=now)
    assert snap["workers_reporting"] == 2
    assert snap["counters"][telemetry.M_ENGINE_ROWS_OUT]["count"] == 8
    gauge = snap["gauges"][telemetry.M_EXECUTOR_QUEUE_DEPTH]
    assert gauge["min"] == 2.0 and gauge["max"] == 7.0
    hist = snap["histograms"][telemetry.M_QUEUE_WAIT_S]
    assert hist["count"] == 5
    assert hist["sum"] == pytest.approx(1.8)
    assert hist["min"] == 0.2 and hist["max"] == 0.8

    # per-worker attribution mirrors each side's own fold
    attr = view.attribution(telemetry.M_QUEUE_WAIT_S, "count",
                            60.0, now=now)
    assert attr == {"sparkdl-cluster-0": 3, "sparkdl-cluster-1": 2}

    # frames carry ONLY declared names (the lint's runtime counterpart)
    frame = _frame(reg_a, "sparkdl-cluster-0", 0)
    for section in ("counters", "gauges", "histograms"):
        for name in frame[section]:
            assert (name in telemetry.CANONICAL_METRIC_NAMES
                    or name.startswith(telemetry.HEALTH_METRIC_PREFIX))


def test_merged_p99_breaches_where_no_single_worker_does(monkeypatch):
    """The aggregate-breach construction, statically: each worker's own
    p99 estimate stays under 1.0 s (one's tail is a single outlier its
    p99 never reaches; the other's p99 bucket estimate clamps to its
    modest max), but the MERGED buckets put the cluster p99 in the high
    bucket with a 1.3 s envelope — a real merged percentile >= 1.0 that
    no worst-worker fold could produce."""
    now = 1002.5
    _fixed_clock(monkeypatch, now)

    reg_a, reg_b = _registry(exemplar_k=4), _registry(exemplar_k=4)
    ctx_a = telemetry.SpanContext(trace_id="run-x", span_id=0xA)
    ctx_b = telemetry.SpanContext(trace_id="run-x", span_id=0xB)
    for v in [0.2] * 99 + [1.3]:
        reg_a.histogram(telemetry.M_QUEUE_WAIT_S).observe(v,
                                                          exemplar=ctx_a)
    for v in [0.2] * 98 + [0.9, 0.9]:
        reg_b.histogram(telemetry.M_QUEUE_WAIT_S).observe(v,
                                                          exemplar=ctx_b)

    view = aggregate.ClusterMetricsView(cadence_s=0.25)
    view.ingest(_frame(reg_a, "w-a", 0), now=now)
    view.ingest(_frame(reg_b, "w-b", 1), now=now)

    attr = view.attribution(telemetry.M_QUEUE_WAIT_S, "p99",
                            30.0, now=now)
    assert all(v is not None and v < 1.0 for v in attr.values())
    merged = view.window_snapshot(30.0, now=now)["histograms"][
        telemetry.M_QUEUE_WAIT_S]
    assert merged["p99"] >= 1.0
    assert merged["max"] == 1.3
    # the merged exemplar reservoir keeps the global tail, spans intact
    top = merged["exemplars"][0]
    assert top["value"] == 1.3 and top["span_id"] == 0xA

    # and the federated watchdog sees exactly that verdict on the view
    rules = [r for r in slo.federated_default_rules(window_s=30.0)
             if r.metric == telemetry.M_QUEUE_WAIT_S]
    (rule,) = rules
    assert rule.name.startswith(slo.FEDERATED_RULE_PREFIX)
    with HealthMonitor("fed-unit") as mon:
        wd = slo.SLOWatchdog(rules, attribution=lambda r: view.attribution(
            r.metric, r.stat, r.window_s, now=now))
        verdicts = wd.evaluate(view, now=now)
    assert verdicts[rule.name]["breached"] is True
    (breach,) = mon.events(health.SLO_BREACH)
    assert breach["rule"] == rule.name
    assert breach["workers"] == attr
    assert breach["exemplars"][0]["value"] == 1.3


# -- clock-skew window alignment (ISSUE 19 satellite) -------------------------

def test_skewed_worker_epochs_rebase_with_no_double_count_no_gap(
        monkeypatch):
    """Workers whose clocks run ±2 ring slots off the coordinator's:
    the clock-handshake offset shipped in each frame rebases every slot
    epoch onto the coordinator's clock, so both workers' samples land
    exactly once (no double-count) in the coordinator slot they really
    happened in (no gap) — even for a window of a SINGLE slot."""
    coord_now = 1002.5  # coordinator epoch 200 on the 5 s ladder

    # worker A's clock is 2 slots AHEAD: local 1012.5, offset = -10 s
    _fixed_clock(monkeypatch, coord_now + 2 * _SPAN_S)
    reg_a = _registry()
    for v in (0.2, 0.2, 0.2):
        reg_a.histogram(telemetry.M_QUEUE_WAIT_S).observe(v)
    reg_a.counter(telemetry.M_ENGINE_ROWS_OUT).inc(3)
    frame_a = _frame(reg_a, "w-ahead", 0,
                     offset_ns=int(-2 * _SPAN_S * 1e9))
    assert frame_a["now_epoch"] == 202

    # worker B's clock is 2 slots BEHIND: local 992.5, offset = +10 s
    _fixed_clock(monkeypatch, coord_now - 2 * _SPAN_S)
    reg_b = _registry()
    for v in (0.9, 0.9):
        reg_b.histogram(telemetry.M_QUEUE_WAIT_S).observe(v)
    reg_b.counter(telemetry.M_ENGINE_ROWS_OUT).inc(2)
    frame_b = _frame(reg_b, "w-behind", 1,
                     offset_ns=int(2 * _SPAN_S * 1e9))
    assert frame_b["now_epoch"] == 198

    view = aggregate.ClusterMetricsView(cadence_s=0.25)
    view.ingest(frame_a, now=coord_now)
    view.ingest(frame_b, now=coord_now)

    # a single-slot window on the coordinator clock: epoch 200 only.
    # Unrebased, A's epoch-202 samples would double in any wider window
    # and B's epoch-198 samples would vanish entirely from this one.
    for window_s in (_SPAN_S, 60.0):
        snap = view.window_snapshot(window_s, now=coord_now)
        hist = snap["histograms"][telemetry.M_QUEUE_WAIT_S]
        assert hist["count"] == 5, f"window {window_s}"
        assert hist["sum"] == pytest.approx(3 * 0.2 + 2 * 0.9)
        rows = snap["counters"][telemetry.M_ENGINE_ROWS_OUT]
        assert rows["count"] == 5
    attr = view.attribution(telemetry.M_QUEUE_WAIT_S, "count",
                            _SPAN_S, now=coord_now)
    assert attr == {"w-ahead": 3, "w-behind": 2}


def test_stale_and_dead_workers_age_out_but_frames_are_retained():
    view = aggregate.ClusterMetricsView(cadence_s=0.1)  # stale after .3
    reg_a, reg_b = _registry(), _registry()
    reg_a.histogram(telemetry.M_QUEUE_WAIT_S).observe(0.2)
    reg_b.histogram(telemetry.M_QUEUE_WAIT_S).observe(0.4)
    view.ingest(_frame(reg_a, "w0", 0), now=100.0)
    view.ingest(_frame(reg_b, "w1", 1), now=100.0)
    assert view.workers_reporting(now=100.0) == 2
    assert view.fresh_workers(now=100.0) == ["w0", "w1"]

    # past the staleness horizon the fold empties — explicitly
    assert view.workers_reporting(now=100.31) == 0
    snap = view.window_snapshot(60.0, now=100.31)
    assert snap["workers_reporting"] == 0
    assert snap["histograms"] == {}

    # a dead worker leaves the fold even while its frame is fresh
    view.ingest(_frame(reg_a, "w0", 0, seq=2), now=200.0)
    view.ingest(_frame(reg_b, "w1", 1, seq=2), now=200.0)
    view.mark_dead("w1")
    assert view.fresh_workers(now=200.0) == ["w0"]
    snap = view.window_snapshot(60.0, now=200.0)
    assert snap["workers_reporting"] == 1
    assert snap["histograms"][telemetry.M_QUEUE_WAIT_S]["count"] == 1

    # ...but its last shipped frame stays retained for the recorder
    frames = view.last_frames()
    assert frames["w1"]["alive"] is False
    assert frames["w1"]["frame"]["seq"] == 2
    status = view.status(now=200.0)
    assert status["workers_reporting"] == 1
    assert status["workers_known"] == 2
    assert status["frames_ingested"] == 4
    prom = view.prometheus_text(now=200.0)
    assert "sparkdl_cluster:workers_reporting 1" in prom


# -- the live legs ------------------------------------------------------------

def _queue_wait_rules():
    return [r for r in slo.federated_default_rules(window_s=10.0)
            if r.metric == telemetry.M_QUEUE_WAIT_S]


def _aggregate_breach_op(batch):
    """Each worker observes a queue-wait profile that keeps its OWN p99
    under the 1.0 s threshold; only the cluster-merged buckets breach.
    The tail values come last so a partial frame never breaches early."""
    tel = telemetry.active()
    wid = int(tel.process_scope[1:]) if tel and tel.process_scope else 0
    vals = ([0.2] * 99 + [1.3]) if wid == 0 else ([0.2] * 98 + [0.9, 0.9])
    ctx = telemetry.current_context()
    for v in vals:
        telemetry.observe(telemetry.M_QUEUE_WAIT_S, v, exemplar=ctx)
    x = np.asarray(batch.column("x"), dtype=np.float64)
    return pa.array(x * 2.0)


def _slow_op(batch):
    time.sleep(0.08)  # outlives the frame cadence: every worker ships
    x = np.asarray(batch.column("x"), dtype=np.float64)
    return pa.array(x * 3.0)


def _collect(op, n=24, parts=4):
    df = DataFrame.fromRows([{"x": i} for i in range(n)],
                            numPartitions=parts)
    return df.withColumnBatch("y", op, outputType=pa.float64()).collect()


def _wait_for(mon, event, deadline_s):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline and not mon.count(event):
        time.sleep(0.1)
    return mon.count(event)


def test_aggregate_breach_fires_live_and_dumps_a_postmortem(
        tmp_path, monkeypatch):
    """The ISSUE 19 acceptance leg: NO single worker breaches the local
    queue-wait SLO, but the cluster-wide merged p99 does. The federated
    watchdog fires DURING the run (exactly one breach/recovered pair),
    the breach names both workers' sub-threshold contributions plus a
    resolvable exemplar span, and the flight recorder lands an atomic
    postmortem bundle on disk BEFORE the run ends."""
    monkeypatch.setattr(cluster_router, "_default_federation_rules",
                        _queue_wait_rules)
    EngineConfig.cluster_workers = 2
    EngineConfig.cluster_federation_s = 0.1
    out = str(tmp_path)
    with HealthMonitor("fed-breach") as mon, \
            Telemetry(name="fed-breach", out_dir=out,
                      exemplar_k=4) as tel:
        try:
            got = _collect(_aggregate_breach_op)
            assert _wait_for(mon, health.SLO_BREACH, 30.0) == 1
            # the bundle is on disk MID-RUN, before any shutdown path
            mid_run = glob.glob(os.path.join(out, "postmortem_*"))
            assert len(mid_run) == 1
            assert not mid_run[0].endswith(".tmp")  # the atomic rename
            assert _wait_for(mon, health.SLO_RECOVERED, 30.0) == 1
        finally:
            cluster_router.shutdown()

    assert [r["y"] for r in got] == [2.0 * i for i in range(24)]
    # exactly ONE breach/recovered pair — partial frames never flapped
    assert mon.count(health.SLO_BREACH) == 1
    assert mon.count(health.SLO_RECOVERED) == 1
    assert mon.count(health.POSTMORTEM_DUMPED) == 1

    (breach,) = mon.events(health.SLO_BREACH)
    assert breach["rule"].startswith(slo.FEDERATED_RULE_PREFIX)
    assert breach["observed"] >= 1.0 > breach["threshold"] - 0.001
    # per-worker attribution: every worker is UNDER the threshold —
    # the breach is a property of the merged view alone
    workers = breach["workers"]
    assert len(workers) == 2
    assert all(v < 1.0 for v in workers.values())
    # the exemplar is a real span in the merged trace
    spans = {s["span_id"] for s in tel.tracer.spans()}
    exemplars = breach["exemplars"]
    assert exemplars[0]["value"] == pytest.approx(1.3)
    assert all(e["trace_id"] == tel.run_id for e in exemplars)
    assert any(e["span_id"] in spans for e in exemplars)

    # the bundle: four artifacts, consistent with the breach
    (bundle,) = glob.glob(os.path.join(out, "postmortem_*"))
    assert os.path.basename(bundle).startswith(
        f"postmortem_{tel.run_id}_")
    assert sorted(os.listdir(bundle)) == [
        "breach.json", "health.json", "snapshots.jsonl", "trace.json"]
    with open(os.path.join(bundle, "breach.json")) as f:
        bj = json.load(f)
    assert bj["trigger"] == "slo_breach"
    assert bj["detail"]["rule"] == breach["rule"]
    assert bj["rings_pulled"] == 2  # both live workers answered
    assert len(bj["federation"]) == 2  # every worker's last frame
    with open(os.path.join(bundle, "trace.json")) as f:
        doc = json.load(f)
    assert {e["pid"] for e in doc["traceEvents"] if e["ph"] == "X"}
    with open(os.path.join(bundle, "snapshots.jsonl")) as f:
        timeline = [json.loads(line) for line in f]
    assert timeline and all("workers_reporting" in t for t in timeline)
    assert any(t["slo"].get(breach["rule"], {}).get("breached")
               for t in timeline)

    # the merged reports carry the federation section + the bundle path
    fed = cluster_router.last_cluster_report()["federation"]
    assert fed["workers_known"] == 2
    assert fed["frames_ingested"] >= 2
    assert fed["postmortems"] == [bundle]
    assert cluster_router.last_run_report()["cluster"]["federation"] \
        == fed


def test_worker_kill_with_federation_armed_keeps_outputs_bit_identical(
        tmp_path):
    """SIGKILL one worker mid-stream with federation armed: outputs are
    bit-identical to the no-cluster run, the dead worker ages out of the
    fold the moment its pipe hits EOF (one cluster_metrics_stale event),
    and the worker-loss postmortem bundle retains its LAST shipped
    frame."""
    want = _collect(_slow_op, 36, 6)

    EngineConfig.cluster_workers = 2
    EngineConfig.cluster_federation_s = 0.04
    out = str(tmp_path)
    inj = FaultInjector.seeded(0, cluster_worker_kill=Fault(times=1,
                                                            after=2))
    with HealthMonitor("fed-chaos") as mon, \
            Telemetry(name="fed-chaos", out_dir=out):
        try:
            with inj:
                got = _collect(_slow_op, 36, 6)
        finally:
            cluster_router.shutdown()

    assert inj.fired == {"cluster_worker_kill": 1}
    assert got == want  # bit-identical THROUGH the loss
    assert mon.count(health.CLUSTER_WORKER_LOST) == 1
    (lost,) = mon.events(health.CLUSTER_WORKER_LOST)
    dead = lost["worker"]

    # the view aged the dead worker out explicitly, exactly once
    (stale,) = mon.events(health.CLUSTER_METRICS_STALE)
    assert stale["worker"] == dead and stale["reason"] == "worker_lost"

    # the worker-loss bundle retains the dead worker's last frame
    assert mon.count(health.POSTMORTEM_DUMPED) == 1
    (bundle,) = glob.glob(os.path.join(out, "postmortem_*"))
    with open(os.path.join(bundle, "breach.json")) as f:
        bj = json.load(f)
    assert bj["trigger"] == "worker_lost"
    assert bj["detail"] == {"worker": dead}
    entry = bj["federation"][dead]
    assert entry["alive"] is False
    assert entry["frame"]["seq"] >= 1
    assert entry["frame"]["worker"] == dead
    # the survivor answered the ring pull; the dead worker cannot
    assert bj["rings_pulled"] == 1

    fed = cluster_router.last_cluster_report()["federation"]
    assert fed["workers_known"] == 2
    assert fed["postmortems"] == [bundle]


# -- the off path -------------------------------------------------------------

def test_federation_off_ships_no_frames_and_reports_stay_shaped(
        tmp_path):
    """cluster_federation_s unset: no frames, no view, no watchdog, no
    postmortems — the cluster report, the merged run report, and the
    exporter artifacts keep their exact pre-federation shape."""
    EngineConfig.cluster_workers = 2
    out = str(tmp_path)
    with Telemetry(name="fed-off", out_dir=out,
                   export_interval_s=30.0) as tel:
        try:
            got = _collect(_slow_op)
            assert cluster_router.exporter_status() is None
            assert cluster_router.exporter_prometheus_text() == ""
        finally:
            cluster_router.shutdown()
    assert len(got) == 24

    assert glob.glob(os.path.join(out, "postmortem_*")) == []
    rep = cluster_router.last_cluster_report()
    assert rep["worker_count"] == 2
    assert "federation" not in rep
    assert "federation" not in cluster_router.last_run_report()["cluster"]
    with open(tel.exporter.snapshot_path) as f:
        for line in f:
            assert "cluster" not in json.loads(line)
    with open(tel.exporter.prom_path) as f:
        # the FEDERATED families (colon-namespaced) never appear; the
        # coordinator's own sparkdl.cluster.* locals of course do
        assert "sparkdl_cluster:" not in f.read()
