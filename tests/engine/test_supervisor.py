"""Engine task supervision: classified retry, attempt history, deadline
watchdog, speculative hedging, quarantine (docs/RESILIENCE.md)."""

import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from sparkdl_tpu.core import health, resilience
from sparkdl_tpu.core.health import HealthMonitor
from sparkdl_tpu.core.resilience import (
    Fault,
    FaultInjector,
    RetryPolicy,
    WorkerFault,
    classify,
)
from sparkdl_tpu.engine import DataFrame, EngineConfig, TaskFailure
from sparkdl_tpu.engine.supervisor import run_partition_task

# full snapshot of every public knob (ISSUE 6: the overload knobs — and
# any future knob — are covered without listing them)
_DEFAULTS = EngineConfig.snapshot()


@pytest.fixture(autouse=True)
def _restore_engine_config():
    yield
    for k, v in _DEFAULTS.items():
        setattr(EngineConfig, k, v)


def make_df(n=12, parts=4):
    return DataFrame.fromRows([{"x": i} for i in range(n)],
                              numPartitions=parts)


FAST = RetryPolicy(max_retries=2, base_delay_s=0.0, jitter=0.0)


# -- classified retry at the task level --------------------------------------

def test_fatal_op_error_never_retried():
    calls = []
    df = make_df(6, 3)

    def bad(x):
        calls.append(x)
        if x == 3:  # lands in partition 1
            raise ValueError("deliberate shape error")
        return x

    out = df.withColumn("y", bad, ["x"], pa.int64())
    with pytest.raises(TaskFailure) as ei:
        out.collect()
    tf = ei.value
    assert tf.failure_kind == resilience.FATAL
    assert tf.retries() == 0
    assert len(tf.attempts) == 1 and tf.attempts[0].kind == resilience.FATAL
    assert "ValueError" in tf.attempts[0].error
    assert calls.count(3) == 1  # provably retried zero times
    # classified wrappers: upstream retry layers must see FATAL
    assert classify(tf) == resilience.FATAL


def test_oom_escaping_ops_not_retried_at_task_level():
    calls = []

    def oom(batch):
        calls.append(1)
        raise resilience.DeviceOOM()

    df = make_df(4, 2).mapPartitions(oom)
    with pytest.raises(TaskFailure) as ei:
        df.collect()
    assert ei.value.failure_kind == resilience.OOM
    assert classify(ei.value) == resilience.OOM
    # 2 partitions, one attempt each — no same-shape OOM replays
    assert len(calls) == 2


def test_retryable_errors_backed_off_with_history():
    failures = {"n": 2}
    lock = threading.Lock()

    def flaky(batch):
        with lock:
            if failures["n"] > 0:
                failures["n"] -= 1
                raise RuntimeError("UNAVAILABLE: worker lost")
        return batch

    with HealthMonitor() as mon:
        assert make_df(4, 1).mapPartitions(flaky).count() == 4
    assert mon.count(health.TASK_RETRIED) == 2


def test_retry_exhaustion_carries_full_attempt_history():
    def always(batch):
        raise RuntimeError("UNAVAILABLE: permanently lost")

    EngineConfig.max_task_retries = 2
    with pytest.raises(TaskFailure) as ei:
        make_df(4, 2).mapPartitions(always).collect()
    tf = ei.value
    assert tf.failure_kind == resilience.RETRYABLE
    assert len(tf.attempts) == 3  # initial + 2 retries
    assert all(a.kind == resilience.RETRYABLE for a in tf.attempts)
    assert all(a.duration_s >= 0 for a in tf.attempts)
    assert tf.index is not None


def test_run_partition_task_backoff_uses_policy(monkeypatch):
    slept = []
    attempts = {"n": 0}

    def flaky(batch):
        attempts["n"] += 1
        if attempts["n"] < 3:
            raise resilience.TransferStall()
        return batch

    policy = RetryPolicy(max_retries=3, base_delay_s=1.0, jitter=0.0)
    out = run_partition_task(0, "batch", [flaky], policy=policy,
                             sleep=slept.append)
    assert out == "batch"
    assert slept == [1.0, 2.0]  # exponential, from the policy


# -- unified fault injection --------------------------------------------------

def test_engine_task_injection_point_recovers_via_retry():
    df = make_df(8, 2).withColumn("y", lambda x: x * 2, ["x"], pa.int64())
    with FaultInjector.seeded(0, engine_task=1) as inj:
        with HealthMonitor() as mon:
            rows = df.collect()
    assert [r["y"] for r in rows] == [2 * i for i in range(8)]
    assert inj.fired["engine_task"] == 1
    assert mon.count(health.TASK_RETRIED) == 1
    assert classify(WorkerFault()) == resilience.RETRYABLE


def test_engine_task_finish_phase_discards_computed_attempt():
    """A worker dying AFTER computing but before delivering its result:
    the retried attempt recomputes and the output is bit-identical."""
    calls = []

    def track(x):
        calls.append(x)
        return x + 1

    df = make_df(6, 1).withColumn("y", track, ["x"], pa.int64())
    with FaultInjector.seeded(0, engine_task=Fault(
            times=1, when=lambda c: c.get("phase") == "finish")) as inj:
        rows = df.collect()
    assert inj.fired["engine_task"] == 1
    assert [r["y"] for r in rows] == [i + 1 for i in range(6)]
    assert calls == list(range(6)) * 2  # attempt 0 discarded, attempt 1 kept


def test_legacy_fault_injector_shim_still_works():
    seen = []

    def injector(pidx, attempt):
        seen.append((pidx, attempt))
        if pidx == 1 and attempt == 0:
            raise RuntimeError("transient")

    EngineConfig.fault_injector = injector
    assert make_df(6, 3).withColumn(
        "y", lambda x: x, ["x"], pa.int64()).count() == 6
    assert (1, 0) in seen and (1, 1) in seen


# -- deadline watchdog --------------------------------------------------------

def test_stalled_task_fails_via_deadline_instead_of_hanging():
    EngineConfig.task_timeout_s = 0.3
    df = make_df(9, 3).withColumn("y", lambda x: x, ["x"], pa.int64())
    t0 = time.monotonic()
    with FaultInjector.seeded(0, task_stall=Fault(
            when=lambda c: c["partition"] == 1)) as inj:
        with HealthMonitor() as mon:
            with pytest.raises(TaskFailure, match="deadline"):
                df.collect()
    elapsed = time.monotonic() - t0
    assert inj.fired["task_stall"] == 1
    assert elapsed < 5.0  # the watchdog fired; no hang
    assert mon.count(health.TASK_DEADLINE_EXCEEDED) == 1
    ev = mon.events(health.TASK_DEADLINE_EXCEEDED)[0]
    assert ev["partition"] == 1


def test_deadline_failure_classified_fatal():
    """DeadlineExceeded is the retry budget — it must not be retried by
    the task loop or any upstream gang boundary."""
    EngineConfig.task_timeout_s = 0.2
    with FaultInjector.seeded(0, task_stall=Fault(
            when=lambda c: c["partition"] == 0)):
        with pytest.raises(TaskFailure) as ei:
            make_df(4, 2).withColumn(
                "y", lambda x: x, ["x"], pa.int64()).collect()
    assert ei.value.failure_kind == resilience.FATAL
    assert classify(ei.value) == resilience.FATAL


def test_cooperative_deadline_on_inline_path():
    """Inline (nested / limit) execution has no watchdog thread; the
    cooperative check between ops still bounds the task."""

    def slow(batch):
        time.sleep(0.3)
        return batch

    with pytest.raises(TaskFailure, match="deadline"):
        run_partition_task(0, pa.RecordBatch.from_pylist([{"x": 1}]),
                           [slow, slow], policy=FAST, deadline_s=0.2)


# -- speculative execution (hedging) ------------------------------------------

def test_straggler_partition_hedged_first_result_wins():
    EngineConfig.speculation = True
    EngineConfig.speculation_quantile = 0.5
    EngineConfig.speculation_min_runtime_s = 0.05
    # fresh, wide pool: a narrow or contaminated shared pool (a sleeper
    # left by an earlier test) would queue the hedge behind the straggler
    EngineConfig.max_workers = 9
    ran = set()
    lock = threading.Lock()

    def op(batch):
        first = batch.column(0)[0].as_py()
        with lock:
            hedge_run = (first in ran)
            ran.add(first)
        if first == 15 and not hedge_run:
            # the PRIMARY attempt of the last partition straggles
            # (environmental slowness: the re-executed copy is fast)
            time.sleep(2.0)
        return batch

    df = DataFrame.fromRows([{"x": i} for i in range(18)], numPartitions=6)
    baseline = df.collect()
    slow = df.mapPartitions(op)
    t0 = time.monotonic()
    with HealthMonitor() as mon:
        rows = slow.collect()
    elapsed = time.monotonic() - t0
    # bit-identical, order-preserving, deduplicated
    assert rows == baseline
    assert mon.count(health.TASK_HEDGED) == 1
    assert mon.count(health.HEDGE_WON) == 1
    assert mon.events(health.TASK_HEDGED)[0]["partition"] == 5
    assert elapsed < 1.5  # the hedge won; nobody waited out the straggler


def test_hedge_loser_bails_quietly_after_task_resolves():
    """A discarded loser must not keep retrying or record failure events
    for a task that already succeeded via its hedge."""
    EngineConfig.speculation = True
    EngineConfig.speculation_quantile = 0.5
    EngineConfig.speculation_min_runtime_s = 0.05
    EngineConfig.max_workers = 10  # fresh, wide pool (see straggler test)
    ran = set()
    lock = threading.Lock()

    def op(batch):
        first = batch.column(0)[0].as_py()
        with lock:
            hedge_run = (first in ran)
            ran.add(first)
        if first == 15 and not hedge_run:
            time.sleep(1.0)
            # the straggling primary then dies retryably — after the
            # hedge already won, this must be swallowed silently
            raise RuntimeError("UNAVAILABLE: straggler worker lost")
        return batch

    df = DataFrame.fromRows([{"x": i} for i in range(18)], numPartitions=6)
    baseline = df.collect()
    with HealthMonitor() as mon:
        rows = df.mapPartitions(op).collect()
        time.sleep(1.3)  # outlive the loser's wake-up with monitor active
    assert rows == baseline
    assert mon.count(health.HEDGE_WON) == 1
    assert mon.count(health.TASK_FAILED) == 0
    assert mon.count(health.TASK_RETRIED) == 0


def test_no_hedging_by_default():
    calls = []
    lock = threading.Lock()

    def op(batch):
        with lock:
            calls.append(1)
        time.sleep(0.05)
        return batch

    with HealthMonitor() as mon:
        make_df(8, 4).mapPartitions(op).collect()
    assert len(calls) == 4  # pure ops run exactly once per partition
    assert mon.count(health.TASK_HEDGED) == 0


# -- quarantine ---------------------------------------------------------------

def _poison_df():
    df = make_df(9, 3)

    def op(x):
        if 3 <= x < 6:  # partition 1's rows are poisoned
            raise ValueError(f"poisoned row {x}")
        return x * 10

    return df.withColumn("y", op, ["x"], pa.int64())


def test_quarantine_off_by_default_fatal_raises():
    with pytest.raises(TaskFailure):
        _poison_df().collect()


def test_quarantine_drops_poisoned_partition_and_records():
    EngineConfig.quarantine = True
    with HealthMonitor() as mon:
        out = _poison_df()
        rows = out.collect()
    # partition 1's rows dropped; survivors keep their values and order
    assert [r["x"] for r in rows] == [0, 1, 2, 6, 7, 8]
    assert [r["y"] for r in rows] == [0, 10, 20, 60, 70, 80]
    # schema intact (the zero-row stand-in ran the op chain)
    assert out.toArrow().schema.field("y").type == pa.int64()
    assert mon.count(health.TASK_QUARANTINED) == 1
    entry = mon.quarantined()[0]
    assert entry["partition"] == 1
    assert entry["attempts"] == [resilience.FATAL]
    # the report surfaces the registry
    assert mon.report()["quarantined"] == [entry]


def test_quarantine_streaming_yields_empty_standin():
    EngineConfig.quarantine = True
    out = _poison_df()
    parts = list(out.streamPartitions())
    assert [p.num_rows for p in parts] == [3, 0, 3]
    assert all("y" in p.schema.names for p in parts)


def test_quarantine_max_fatal_confirms_poison_before_dropping():
    """quarantine_max_fatal=2: the deterministic failure is replayed once
    to confirm the poison, then the partition drops with both fatal
    attempts on record."""
    EngineConfig.quarantine = True
    EngineConfig.quarantine_max_fatal = 2
    calls = []

    def bad(x):
        if 3 <= x < 6:
            calls.append(x)
            raise ValueError(f"poisoned row {x}")
        return x

    with HealthMonitor() as mon:
        rows = make_df(9, 3).withColumn("y", bad, ["x"], pa.int64()).collect()
    assert [r["x"] for r in rows] == [0, 1, 2, 6, 7, 8]
    assert calls == [3, 3]  # exactly two confirmation attempts
    entry = mon.quarantined()[0]
    assert entry["attempts"] == [resilience.FATAL, resilience.FATAL]


def test_deadline_failure_not_quarantined():
    """A timeout is slowness, not poison: quarantine must not silently
    drop a transiently stalled partition's rows."""
    EngineConfig.quarantine = True
    EngineConfig.task_timeout_s = 0.2
    with FaultInjector.seeded(0, task_stall=Fault(
            when=lambda c: c["partition"] == 1)):
        with HealthMonitor() as mon:
            with pytest.raises(TaskFailure, match="deadline"):
                make_df(6, 3).withColumn(
                    "y", lambda x: x, ["x"], pa.int64()).collect()
    assert mon.count(health.TASK_QUARANTINED) == 0


def test_cooperative_deadline_expiry_not_quarantined():
    """A task whose op chain crosses the budget BETWEEN watchdog ticks
    fails via the cooperative check — still a timeout, still excluded
    from quarantine (no silent row loss on a transient straggle)."""
    EngineConfig.quarantine = True
    EngineConfig.task_timeout_s = 0.15

    def slow(batch):
        time.sleep(0.05)
        return batch

    # 4 sequential ops x 50ms > 150ms: expiry hits the cooperative check
    df = make_df(4, 1)
    for _ in range(4):
        df = df.mapPartitions(slow)
    with HealthMonitor() as mon:
        with pytest.raises(TaskFailure, match="deadline") as ei:
            df.collect()
    assert ei.value.deadline_exceeded
    assert mon.count(health.TASK_QUARANTINED) == 0
    assert mon.count(health.TASK_DEADLINE_EXCEEDED) == 1


def test_watchdog_deadline_counted_once_after_stalled_thread_wakes():
    """The wedged worker thread must not record a second deadline event
    (or keep retrying) after the watchdog abandoned its task."""
    EngineConfig.task_timeout_s = 0.2
    df = make_df(6, 3).withColumn("y", lambda x: x, ["x"], pa.int64())
    with FaultInjector.seeded(0, task_stall=Fault(
            when=lambda c: c["partition"] == 1)):
        with HealthMonitor() as mon:
            with pytest.raises(TaskFailure, match="deadline"):
                df.collect()
            # outlive the stall's wake-up (~2x budget + margin) with the
            # monitor still active
            time.sleep(1.2)
    assert mon.count(health.TASK_DEADLINE_EXCEEDED) == 1
    assert mon.count(health.TASK_RETRIED) == 0


def test_quarantine_never_applies_to_retryable_exhaustion():
    EngineConfig.quarantine = True
    EngineConfig.max_task_retries = 1

    def flaky(batch):
        raise RuntimeError("UNAVAILABLE: still down")

    with pytest.raises(TaskFailure) as ei:
        make_df(4, 2).mapPartitions(flaky).collect()
    assert ei.value.failure_kind == resilience.RETRYABLE


# -- streamPartitions: cancellation + sharded supervision ---------------------

def test_abandoned_stream_cancels_unstarted_partitions():
    EngineConfig.max_workers = 1  # narrow pool: prefetch window queues
    executed = []
    lock = threading.Lock()

    def op(batch):
        with lock:
            executed.append(batch.column(0)[0].as_py())
        time.sleep(0.05)
        return batch

    df = DataFrame.fromRows([{"x": i} for i in range(12)],
                            numPartitions=6).mapPartitions(op)
    gen = df.streamPartitions(prefetch=4)
    next(gen)
    gen.close()  # early abandon: unstarted window tasks must be cancelled
    with lock:
        n = len(executed)
    assert n <= 3  # yielded head + at most the in-flight attempt(s)


def test_stream_order_and_process_sharding_survive_injected_faults():
    """A failing-then-recovering shard on one 'host' must not corrupt the
    round-robin assignment or reorder surviving partitions."""
    df = DataFrame.fromColumns({"v": np.arange(24, dtype=np.int64)},
                               numPartitions=8)
    df = df.withColumn("w", lambda v: v + 1, inputCols=["v"])
    order = [5, 2, 7, 0, 3, 6, 1, 4]
    expect = {p: [order[p::3][j] for j in range(len(order[p::3]))]
              for p in range(3)}

    def first_values(p, injector=None):
        if injector is None:
            return [b.column(0).to_pylist()
                    for b in df.streamPartitions(order=order, process_id=p,
                                                 num_processes=3)]
        with injector:
            return [b.column(0).to_pylist()
                    for b in df.streamPartitions(order=order, process_id=p,
                                                 num_processes=3)]

    clean = {p: first_values(p) for p in range(3)}
    # host 1's first task fails twice retryably, then recovers
    inj = FaultInjector.seeded(0, engine_task=2)
    faulted = {p: first_values(p, injector=inj if p == 1 else None)
               for p in range(3)}
    assert inj.fired["engine_task"] == 2
    assert faulted == clean
    # assignment partitions the dataset: disjoint + exhaustive
    seen = [v for host in faulted.values() for part in host for v in part]
    assert sorted(seen) == list(range(24))
    for p in range(3):
        starts = [part[0] for part in faulted[p]]
        natural = [b.column(0).to_pylist()[0]
                   for b in df.streamPartitions()]
        assert starts == [natural[i] for i in expect[p]]


def test_sharded_stream_quarantine_degrades_only_owning_host():
    EngineConfig.quarantine = True
    df = DataFrame.fromColumns({"v": np.arange(12, dtype=np.int64)},
                               numPartitions=4)

    def op(v):
        if v == 3:  # partition 1 is poisoned
            raise ValueError("poisoned")
        return v

    df = df.withColumn("w", op, inputCols=["v"])
    host0 = [b.column(0).to_pylist()
             for b in df.streamPartitions(process_id=0, num_processes=2)]
    host1 = [b.column(0).to_pylist()
             for b in df.streamPartitions(process_id=1, num_processes=2)]
    assert host0 == [[0, 1, 2], [6, 7, 8]]  # untouched
    assert host1 == [[], [9, 10, 11]]  # partition 1 dropped, order kept


def test_retry_loop_attempt_restarts_executor_call_sequence():
    """Each retry-loop attempt re-runs the op chain from the top, so its
    device calls restart at call 0 — run_partition_task must realign the
    executor's hedge-dedup sequence per attempt, or a retried primary's
    call 0 would sit at seq N and a hedge's call N could cross-dedup onto
    the wrong device call's output (core/executor.py)."""
    from sparkdl_tpu.core.executor import current_task_token, task_scope

    seen = []
    failures = {"n": 1}

    def device_call(batch):
        seen.append(current_task_token())
        if failures["n"] > 0:
            failures["n"] -= 1
            raise RuntimeError("UNAVAILABLE: transient")
        return batch

    with task_scope(("task", 7, 0)):
        out = run_partition_task(0, "rows", [device_call, device_call],
                                 FAST)
    assert out == "rows"
    # attempt 0: call 0 raised; attempt 1: calls 0 and 1 — the retried
    # attempt's sequence restarted at 0 instead of continuing at 1
    assert seen == [("task", 7, 0, 0), ("task", 7, 0, 0),
                    ("task", 7, 0, 1)]
