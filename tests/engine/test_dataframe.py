"""Engine DataFrame tests — partitioned execution, retry, columnar UDFs."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from sparkdl_tpu.engine import DataFrame, EngineConfig, TaskFailure
from sparkdl_tpu.engine.dataframe import column_to_numpy, fixed_size_list_array


def make_df(n=10, parts=3):
    return DataFrame.fromPandas(
        pd.DataFrame({"x": np.arange(n, dtype=np.int64),
                      "y": np.arange(n, dtype=np.float64) * 2.0}),
        numPartitions=parts)


def test_partitioning_and_count():
    df = make_df(10, 3)
    assert df.numPartitions == 3
    assert df.count() == 10
    assert df.columns == ["x", "y"]


def test_collect_order_preserved():
    df = make_df(10, 4)
    rows = df.collect()
    assert [r["x"] for r in rows] == list(range(10))


def test_select_drop_rename():
    df = make_df()
    assert df.select("y").columns == ["y"]
    assert df.drop("x").columns == ["y"]
    assert df.withColumnRenamed("x", "z").columns == ["z", "y"]
    with pytest.raises(KeyError):
        df.select("nope")


def test_with_column_rowwise():
    df = make_df(6, 2)
    out = df.withColumn("sum", lambda x, y: float(x) + y,
                        inputCols=["x", "y"], outputType=pa.float64())
    rows = out.collect()
    assert all(r["sum"] == r["x"] + r["y"] for r in rows)


def test_with_column_batch_vectorized():
    df = make_df(8, 3)

    def double(batch: pa.RecordBatch) -> pa.Array:
        x = column_to_numpy(batch.column(0))
        return pa.array(x * 2)

    rows = df.withColumnBatch("x2", double, outputType=pa.int64()).collect()
    assert all(r["x2"] == 2 * r["x"] for r in rows)


def test_filter_and_dropna():
    df = make_df(10, 2)
    assert df.filter(lambda x: x % 2 == 0, inputCols=["x"]).count() == 5
    df2 = DataFrame.fromRows([{"a": 1}, {"a": None}, {"a": 3}])
    assert df2.dropna().count() == 2


def test_limit_union_repartition():
    df = make_df(10, 3)
    assert df.limit(4).count() == 4
    assert df.union(make_df(5, 1)).count() == 15
    assert df.repartition(5).numPartitions == 5
    assert df.repartition(5).count() == 10


def test_lazy_ops_compose():
    df = make_df(10, 2)
    out = (df.withColumn("a", lambda x: x + 1, ["x"], pa.int64())
             .withColumn("b", lambda a: a * 10, ["a"], pa.int64())
             .select("b"))
    assert [r["b"] for r in out.collect()] == [(i + 1) * 10 for i in range(10)]


def test_retry_recovers_transient_failure():
    df = make_df(6, 3)
    failures = {"left": 1}

    def injector(pidx, attempt):
        if pidx == 1 and failures["left"] > 0:
            failures["left"] -= 1
            raise RuntimeError("transient")

    EngineConfig.fault_injector = injector
    try:
        assert df.withColumn("z", lambda x: x, ["x"], pa.int64()).count() == 6
    finally:
        EngineConfig.fault_injector = None


def test_retry_exhaustion_raises():
    df = make_df(6, 3)

    def injector(pidx, attempt):
        if pidx == 0:
            raise RuntimeError("permanent")

    EngineConfig.fault_injector = injector
    try:
        with pytest.raises(TaskFailure):
            df.withColumn("z", lambda x: x, ["x"], pa.int64()).count()
    finally:
        EngineConfig.fault_injector = None


def test_fixed_size_list_roundtrip(rng):
    mat = rng.standard_normal((5, 7)).astype(np.float32)
    arr = fixed_size_list_array(mat)
    assert arr.type == pa.list_(pa.float32(), 7)
    back = column_to_numpy(arr)
    np.testing.assert_array_equal(mat, back)


def test_from_columns_ndarray(rng):
    feats = rng.standard_normal((4, 3)).astype(np.float32)
    df = DataFrame.fromColumns({"id": list(range(4)), "f": feats})
    back = column_to_numpy(df.toArrow().column("f"))
    np.testing.assert_array_equal(back, feats)


def test_cache_materializes_once():
    calls = {"n": 0}
    df = make_df(4, 2)

    def op(batch):
        calls["n"] += 1
        return pa.array([1] * batch.num_rows)

    out = df.withColumnBatch("one", op, pa.int64()).cache()
    out.collect()
    out.collect()
    assert calls["n"] == 2  # once per partition, not per collect


def test_with_column_no_output_type_then_select():
    # Regression: declared null-typed schema must not be forced onto batches.
    df = make_df(6, 2)
    out = df.withColumn("name", lambda x: f"row{x}", ["x"]).select("name")
    assert [r["name"] for r in out.collect()] == [f"row{i}" for i in range(6)]


def test_heterogeneous_inferred_types_unify():
    # Partition 0 infers null type, partition 1 infers int64 -> unify.
    df = DataFrame.fromRows([{"x": 1}, {"x": 2}], numPartitions=2)
    out = df.withColumn("y", lambda x: None if x == 1 else x, ["x"])
    rows = out.collect()
    assert rows[0]["y"] is None and rows[1]["y"] == 2


def test_cache_reused_by_derived_frames():
    calls = {"n": 0}
    df = make_df(4, 2)

    def op(batch):
        calls["n"] += 1
        return pa.array([1.0] * batch.num_rows)

    cached = df.withColumnBatch("c", op, pa.float64()).cache()
    n_after_cache = calls["n"]
    cached.select("c").collect()
    assert calls["n"] == n_after_cache  # derived frame reused materialization


def test_with_column_replace_keeps_position():
    df = DataFrame.fromRows([{"a": 1, "b": 2}], numPartitions=1)
    out = df.withColumn("a", lambda a: a * 10, ["a"], pa.int64())
    assert out.columns == ["a", "b"]
    assert out.collect() == [{"a": 10, "b": 2}]


def test_limit_materializes_only_needed_partitions():
    calls = {"n": 0}

    def op(batch):
        calls["n"] += 1
        return pa.array([1] * batch.num_rows)

    big = DataFrame.fromRows([{"x": i} for i in range(100)], numPartitions=10)
    assert big.withColumnBatch("y", op, pa.int64()).limit(5).count() == 5
    assert calls["n"] == 1


def test_select_expr_star_literals_aliases(rng):
    df = DataFrame.fromColumns({"a": np.arange(4, dtype=np.int64),
                                "b": np.arange(4, dtype=np.float32)})
    out = df.selectExpr("*", "7 as seven", "'x' as tag", "a as a2")
    rows = out.collect()
    assert out.columns == ["a", "b", "seven", "tag", "a2"]
    assert rows[0]["seven"] == 7 and rows[0]["tag"] == "x"
    assert [r["a2"] for r in rows] == [0, 1, 2, 3]


def test_select_expr_nested_and_multi_arg_udfs(rng):
    from sparkdl_tpu.udf import registerUDF, udf_registry

    registerUDF("sq_test", lambda v: v * v)
    registerUDF("addc_test", lambda a, b: a + b, arity=2)
    try:
        df = DataFrame.fromColumns({"x": np.arange(4, dtype=np.int64),
                                    "y": np.arange(4, dtype=np.int64)})
        out = df.selectExpr("addc_test(sq_test(x), y) as z").collect()
        assert [r["z"] for r in out] == [0, 2, 6, 12]
        # default name is the trimmed expression text
        out2 = df.selectExpr("sq_test( x )")
        assert out2.columns == ["sq_test( x )"]
    finally:
        udf_registry.unregister("sq_test")
        udf_registry.unregister("addc_test")


def test_select_expr_arity_and_parse_errors(rng):
    from sparkdl_tpu.udf import registerUDF, udf_registry

    registerUDF("one_arg_test", lambda v: v)
    try:
        df = DataFrame.fromColumns({"x": np.arange(3, dtype=np.int64)})
        with pytest.raises(ValueError, match="argument"):
            df.selectExpr("one_arg_test(x, x)")
        with pytest.raises(ValueError, match="Cannot tokenize|Unexpected|Trailing"):
            df.selectExpr("x + 1")
        with pytest.raises(KeyError, match="nope"):
            df.selectExpr("nope")
    finally:
        udf_registry.unregister("one_arg_test")


def test_stream_partitions_order(rng):
    df = DataFrame.fromColumns({"v": np.arange(12, dtype=np.int64)},
                               numPartitions=4)
    df = df.withColumn("w", lambda v: v + 1, inputCols=["v"])
    natural = [p.column(0).to_pylist() for p in df.streamPartitions()]
    order = [2, 0, 3, 1]
    permuted = [p.column(0).to_pylist()
                for p in df.streamPartitions(order=order)]
    assert permuted == [natural[i] for i in order]
    # cached frames honor order too
    df.cache().collect() if hasattr(df, "cache") else None
    df2 = df
    df2.toArrow()  # materializes
    permuted2 = [p.column(0).to_pylist()
                 for p in df2.streamPartitions(order=order)]
    assert permuted2 == permuted


def test_order_by():
    df = DataFrame.fromRows(
        [{"a": 3, "b": "x"}, {"a": 1, "b": "y"}, {"a": 2, "b": "z"}],
        numPartitions=2)
    assert [r["a"] for r in df.orderBy("a").collect()] == [1, 2, 3]
    assert [r["a"] for r in df.orderBy("a", ascending=False).collect()] == \
        [3, 2, 1]
    with pytest.raises(KeyError):
        df.orderBy("nope")


def test_order_by_multi_key():
    rows = [{"g": "b", "v": 1}, {"g": "a", "v": 2}, {"g": "a", "v": 1}]
    df = DataFrame.fromRows(rows)
    got = df.orderBy("g", "v", ascending=[True, False]).collect()
    assert [(r["g"], r["v"]) for r in got] == [("a", 2), ("a", 1), ("b", 1)]


def test_group_by_count_and_agg():
    rows = [{"g": "a", "v": 1.0}, {"g": "a", "v": 3.0}, {"g": "b", "v": 5.0}]
    df = DataFrame.fromRows(rows, numPartitions=2)
    counts = {r["g"]: r["count"] for r in df.groupBy("g").count().collect()}
    assert counts == {"a": 2, "b": 1}
    sums = {r["g"]: r["sum(v)"]
            for r in df.groupBy("g").agg({"v": "sum"}).collect()}
    assert sums == {"a": 4.0, "b": 5.0}
    out = df.groupBy("g").agg({"v": "mean"}).orderBy("g").collect()
    assert out[0]["mean(v)"] == 2.0 and out[1]["mean(v)"] == 5.0
    with pytest.raises(ValueError, match="Unsupported aggregate"):
        df.groupBy("g").agg({"v": "median"})


def test_group_by_convenience_mean_sum():
    rows = [{"g": 1, "v": 2.0}, {"g": 1, "v": 4.0}, {"g": 2, "v": 10.0}]
    df = DataFrame.fromRows(rows)
    m = {r["g"]: r["mean(v)"] for r in df.groupBy("g").mean("v").collect()}
    assert m == {1: 3.0, 2: 10.0}
    s = {r["g"]: r["sum(v)"] for r in df.groupBy("g").sum("v").collect()}
    assert s == {1: 6.0, 2: 10.0}


# -- multi-host transform primitives (VERDICT r4 #1) ------------------------

def test_process_shard_partitions_and_idempotence():
    import pyarrow as pa

    from sparkdl_tpu.engine.dataframe import DataFrame

    df = DataFrame.fromRows([{"i": i} for i in range(12)], numPartitions=4)
    shards = [df.processShard(process_id=p, num_processes=3)
              for p in range(3)]
    seen = [set(r["i"] for r in s.collect()) for s in shards]
    assert set().union(*seen) == set(range(12))
    assert sum(len(s) for s in seen) == 12  # disjoint + exhaustive
    # lazy ops on a shard keep provenance and don't re-shard
    derived = shards[0].select("i")
    assert derived._process_shard == (0, 3)
    assert derived.processShard(process_id=1, num_processes=3) is derived
    # single process is a no-op
    assert df.processShard(process_id=0, num_processes=1) is df
    with pytest.raises(ValueError, match="process_id"):
        df.processShard(process_id=3, num_processes=3)


def test_reinterleave_shards_restores_order():
    import pyarrow as pa

    from sparkdl_tpu.engine.dataframe import (DataFrame,
                                              _deserialize_batches,
                                              _reinterleave_shards,
                                              _serialize_batches)

    df = DataFrame.fromRows([{"i": i} for i in range(10)], numPartitions=5)
    n = 2
    per_host = []
    for p in range(n):
        shard = df.processShard(process_id=p, num_processes=n)
        payload = _serialize_batches(shard._materialize(), shard.schema)
        per_host.append(_deserialize_batches(payload))
    parts, schema = _reinterleave_shards(per_host, df.schema)
    rebuilt = DataFrame(parts, schema)
    assert [r["i"] for r in rebuilt.collect()] == list(range(10))


# -- SQL serving surface: where(), temp views, sql() (VERDICT r4 #10) -------

def test_where_comparisons_and_null_semantics():
    from sparkdl_tpu.engine.dataframe import DataFrame

    rows = [{"i": 0, "s": "a", "x": 1.0}, {"i": 1, "s": "b", "x": None},
            {"i": 2, "s": "a", "x": 3.0}, {"i": 3, "s": None, "x": 4.0}]
    df = DataFrame.fromRows(rows, numPartitions=2)
    assert [r["i"] for r in df.where("i >= 2").collect()] == [2, 3]
    assert [r["i"] for r in df.where("s = 'a'").collect()] == [0, 2]
    assert [r["i"] for r in df.where("s != 'a'").collect()] == [1]
    # NULL comparisons are not-true (SQL semantics): row 1 (x NULL) and
    # row 3 (s NULL) drop from comparisons on those columns
    assert [r["i"] for r in df.where("x < 10").collect()] == [0, 2, 3]
    assert [r["i"] for r in df.where("x IS NULL").collect()] == [1]
    assert [r["i"] for r in df.where("s is not null AND x > 1").collect()] \
        == [2]
    assert [r["i"] for r in df.where("i = 0 OR (i > 1 AND s = 'a')")
            .collect()] == [0, 2]
    assert [r["i"] for r in df.where("NOT i < 2").collect()] == [2, 3]
    with pytest.raises(KeyError, match="nope"):
        df.where("nope = 1")
    with pytest.raises(ValueError, match="WHERE"):
        df.where("f(i) = 1")


def test_sql_over_temp_view():
    from sparkdl_tpu.engine.dataframe import DataFrame, sql, table

    rows = [{"i": i, "lab": i % 2} for i in range(6)]
    df = DataFrame.fromRows(rows, numPartitions=2)
    df.createOrReplaceTempView("rows_view")
    assert table("rows_view") is df
    out = sql("SELECT i, lab AS y FROM rows_view WHERE lab = 1").collect()
    assert [r["i"] for r in out] == [1, 3, 5]
    assert all(set(r) == {"i", "y"} for r in out)
    # star + literal projection, keyword case-insensitivity
    out = sql("select *, 7 as seven from rows_view where i >= 4").collect()
    assert [(r["i"], r["seven"]) for r in out] == [(4, 7), (5, 7)]
    with pytest.raises(KeyError, match="no_view"):
        sql("SELECT i FROM no_view")
    with pytest.raises(ValueError, match="SELECT"):
        sql("UPDATE rows_view")


def test_sql_with_registered_udf(rng):
    """The reference's exact serving string (SURVEY.md §3.4):
    SELECT udf(image_col) FROM view, via a registered tensor UDF."""
    from sparkdl_tpu.core.model_function import ModelFunction, TensorSpec
    from sparkdl_tpu.engine.dataframe import DataFrame, sql
    from sparkdl_tpu.udf import registerTensorUDF

    import jax.numpy as jnp

    mf = ModelFunction(lambda v, x: x * v["scale"] + 1.0,
                       {"scale": jnp.asarray(2.0)},
                       TensorSpec((None, 3), "float32"), name="affine")
    registerTensorUDF("affine_udf", mf, batchSize=4)
    x = rng.normal(size=(5, 3)).astype(np.float32)
    df = DataFrame.fromColumns({"vec": x, "keep": np.arange(5)})
    df.createOrReplaceTempView("tensors")
    out = sql("SELECT affine_udf(vec) AS out, keep FROM tensors "
              "WHERE keep != 2").collect()
    assert [r["keep"] for r in out] == [0, 1, 3, 4]
    want = x * 2.0 + 1.0
    for r in out:
        np.testing.assert_allclose(r["out"], want[r["keep"]], rtol=1e-6)


def test_where_constant_predicate():
    from sparkdl_tpu.engine.dataframe import DataFrame

    df = DataFrame.fromRows([{"i": i} for i in range(4)], numPartitions=2)
    assert len(df.where("1 = 1").collect()) == 4
    assert len(df.where("1 = 2").collect()) == 0


def test_distinct_and_sample():
    from sparkdl_tpu.engine.dataframe import DataFrame

    rows = [{"a": i % 3, "b": "x" if i % 2 else "y"} for i in range(12)]
    df = DataFrame.fromRows(rows, numPartitions=3)
    d = df.distinct().collect()
    assert len(d) == 6  # 3 x 2 combinations
    assert len({(r["a"], r["b"]) for r in d}) == 6
    # first-occurrence order
    assert d[0] == {"a": 0, "b": "y"} and d[1] == {"a": 1, "b": "x"}

    big = DataFrame.fromRows([{"i": i} for i in range(1000)],
                             numPartitions=4)
    s = big.sample(0.3, seed=7)
    n = s.count()
    assert 230 <= n <= 370  # Bernoulli around 300
    # deterministic in seed
    assert [r["i"] for r in big.sample(0.3, seed=7).collect()] == \
        [r["i"] for r in s.collect()]
    with pytest.raises(ValueError, match="fraction"):
        big.sample(1.5)


def test_distinct_nested_columns():
    from sparkdl_tpu.engine.dataframe import DataFrame

    rows = [{"s": {"k": [1, 2]}}, {"s": {"k": [1, 2]}}, {"s": {"k": [3]}}]
    df = DataFrame.fromRows(rows, numPartitions=2)
    assert len(df.distinct().collect()) == 2


def test_join_inner_left_and_guards():
    from sparkdl_tpu.engine.dataframe import DataFrame

    left = DataFrame.fromRows(
        [{"id": 1, "x": "a"}, {"id": 2, "x": "b"}, {"id": 2, "x": "c"},
         {"id": 3, "x": "d"}, {"id": None, "x": "e"}], numPartitions=2)
    right = DataFrame.fromRows(
        [{"id": 1, "y": 10}, {"id": 2, "y": 20}, {"id": 2, "y": 21},
         {"id": 9, "y": 90}, {"id": None, "y": 99}], numPartitions=2)

    inner = left.join(right, on="id").collect()
    # id=1 -> 1 pair; id=2 -> 2 left x 2 right = 4 pairs; nulls never match
    assert len(inner) == 5
    assert {(r["id"], r["x"], r["y"]) for r in inner} == {
        (1, "a", 10), (2, "b", 20), (2, "b", 21), (2, "c", 20),
        (2, "c", 21)}
    assert set(inner[0]) == {"id", "x", "y"}  # key appears once

    lj = left.join(right, on="id", how="left").collect()
    assert len(lj) == 7  # 5 matches + id=3 + null-key row
    unmatched = [r for r in lj if r["y"] is None]
    assert {r["x"] for r in unmatched} == {"d", "e"}

    with pytest.raises(ValueError, match="duplicate columns"):
        left.join(DataFrame.fromRows([{"id": 1, "x": "z"}]), on="id")
    with pytest.raises(KeyError, match="right"):
        left.join(DataFrame.fromRows([{"k": 1}]), on="id")
    with pytest.raises(ValueError, match="how"):
        left.join(right, on="id", how="outer")
    # empty result keeps the joined schema
    empty = DataFrame.fromRows([{"id": 77, "x": "q"}]).join(right, on="id")
    assert empty.count() == 0
    assert empty.columns == ["id", "x", "y"]


def test_join_multi_key():
    from sparkdl_tpu.engine.dataframe import DataFrame

    left = DataFrame.fromRows([{"a": 1, "b": "u", "x": 1.0},
                               {"a": 1, "b": "v", "x": 2.0}])
    right = DataFrame.fromRows([{"a": 1, "b": "u", "y": 5.0}])
    out = left.join(right, on=["a", "b"]).collect()
    assert out == [{"a": 1, "b": "u", "x": 1.0, "y": 5.0}]


def test_join_preserves_types_and_order():
    import pyarrow as pa

    from sparkdl_tpu.engine.dataframe import DataFrame

    # key column NOT leftmost; unmatched left join must keep right's
    # int64 dtype (all-null column would otherwise infer as null type)
    left = DataFrame.fromRows([{"x": "a", "id": 7}], numPartitions=1)
    right = DataFrame.fromRows([{"id": 1, "y": 10}], numPartitions=1)
    out = left.join(right, on="id", how="left")
    assert out.columns == ["x", "id", "y"]
    table = out.toArrow()
    assert table.schema.field("y").type == pa.int64()
    assert out.collect() == [{"x": "a", "id": 7, "y": None}]
    # matched and unmatched results share one column order
    both = DataFrame.fromRows([{"x": "a", "id": 1}]).join(right, on="id")
    assert both.columns == ["x", "id", "y"]
    # feature-vector columns survive a join with their list type
    feats = DataFrame.fromColumns({"f": np.ones((2, 4), np.float32),
                                   "id": np.asarray([1, 2])})
    joined = feats.join(right, on="id").toArrow()
    assert pa.types.is_fixed_size_list(joined.schema.field("f").type)


def test_join_on_nested_key():
    from sparkdl_tpu.engine.dataframe import DataFrame

    left = DataFrame.fromRows([{"k": [1, 2], "x": "a"},
                               {"k": [3], "x": "b"}])
    right = DataFrame.fromRows([{"k": [1, 2], "y": 1.0}])
    out = left.join(right, on="k").collect()
    assert out == [{"k": [1, 2], "x": "a", "y": 1.0}]


def test_eval_bool_short_circuits():
    """AND/OR stop at the first deciding operand: the right side references
    a column missing from the env, so evaluating it would KeyError."""
    from sparkdl_tpu.engine import sql_expr

    and_node = sql_expr.parse_bool("a = 1 AND missing = 2")
    assert sql_expr.eval_bool(and_node, {"a": 2}) is False  # no KeyError
    or_node = sql_expr.parse_bool("a = 1 OR missing = 2")
    assert sql_expr.eval_bool(or_node, {"a": 1}) is True
    # an undecided AND/OR must still evaluate everything
    with pytest.raises(KeyError):
        sql_expr.eval_bool(and_node, {"a": 1})
    # SQL UNKNOWN semantics preserved after the rewrite
    null_and = sql_expr.parse_bool("a = 1 AND b = 2")
    assert sql_expr.eval_bool(null_and, {"a": None, "b": 2}) is None
    assert sql_expr.eval_bool(null_and, {"a": None, "b": 3}) is False
    null_or = sql_expr.parse_bool("a = 1 OR b = 2")
    assert sql_expr.eval_bool(null_or, {"a": None, "b": 2}) is True
    assert sql_expr.eval_bool(null_or, {"a": None, "b": 3}) is None
