"""The static-analysis subsystem (ISSUE 8): tier-1 gate + framework
self-tests.

``test_analyzer_clean_on_package`` is the gate: the FULL rule catalog
(concurrency discipline + the migrated lints + suppression hygiene)
runs over ``sparkdl_tpu/`` and must report zero unsuppressed findings —
every future PR passes through it via the tier-1 command. The rest
pins the framework contract: suppression grammar (wrong rule name or a
missing justification does not suppress), baseline round-trip, CLI exit
codes (0 clean / 1 findings / 2 usage), the ``--json`` schema, and a
fixture package under ``tests/fixtures/analysis/`` seeding one
violation per registered rule so no rule can go silently inert.
"""

import json
import pathlib

from sparkdl_tpu import analysis
from sparkdl_tpu.analysis import baseline as baseline_mod
from sparkdl_tpu.analysis import cli, framework

REPO = pathlib.Path(__file__).resolve().parent.parent
PACKAGE = REPO / "sparkdl_tpu"
FIXTURES = REPO / "tests" / "fixtures" / "analysis"


# ---------------------------------------------------------------------------
# The tier-1 gate
# ---------------------------------------------------------------------------


def test_analyzer_clean_on_package():
    """`python -m sparkdl_tpu.analysis` must exit 0 on the repo: every
    hazard is fixed or carries a justified inline suppression."""
    res = analysis.analyze(paths=[PACKAGE])
    listing = "\n".join(str(f) for f in res.findings)
    assert not res.findings, (
        "unsuppressed analyzer findings in sparkdl_tpu/ — fix the "
        "hazard or add '# sparkdl: allow(<rule>): <why>' with a real "
        f"justification (docs/ANALYSIS.md):\n{listing}")
    # the run is not vacuous: it saw the whole package and the known
    # intentional patterns arrived as justified suppressions
    assert res.files > 50
    assert len(res.suppressed) >= 5
    assert all(why for _f, why in res.suppressed)


def test_every_package_suppression_is_justified():
    """No bare `allow(...)` anywhere in the tree (the hygiene rule
    enforces this at analyze time; this pins it directly)."""
    sources = analysis.collect_sources([PACKAGE])
    sups = [(src.rel, sup) for src in sources
            for sup in src.suppressions()]
    assert sups, "expected at least one suppression in the tree"
    for rel, sup in sups:
        assert sup.justification, (
            f"{rel}:{sup.line}: suppression without a justification")


def test_shipped_baseline_is_empty():
    """Policy: fix or suppress inline; the baseline is for emergencies
    and ships empty (zero unexplained baseline entries)."""
    data = json.loads(baseline_mod.DEFAULT_BASELINE_PATH.read_text())
    assert data["entries"] == []


# ---------------------------------------------------------------------------
# Fixture package: one seeded violation per registered rule
# ---------------------------------------------------------------------------

EXPECTED_FIXTURE_RULES = {
    "lock_order_cycle.py": {"lock-order"},
    "wait_foreign_lock.py": {"wait-holding-lock"},
    "blocking_under_lock.py": {"blocking-under-lock"},
    "unguarded_write.py": {"unguarded-shared-write"},
    "thread_lifecycle.py": {"thread-lifecycle"},
    "process_lifecycle.py": {"thread-lifecycle"},
    "broad_retry.py": {"broad-retry"},
    "ml/choke_point.py": {"executor-choke-point"},
    "ml/precision_donation.py": {"executor-choke-point"},
    "ml/row_hop.py": {"columnar-hot-path"},
    "serving/hot_path.py": {"executor-choke-point"},
    "serving/untagged_execute.py": {"tenant-tag"},
    "serving/untagged_cluster_dispatch.py": {"tenant-tag"},
    "cluster/worker_loop.py": {"executor-choke-point",
                               "thread-lifecycle"},
    "trainer_fetch.py": {"blocking-fetch-in-fit"},
    "span_name_typo.py": {"span-names"},
    "remote_span_name.py": {"span-names"},
    "health_bare_string.py": {"health-constants"},
    "slo_metric_typo.py": {"slo-metrics"},
    "federated_frame_key.py": {"slo-metrics"},
    "state/durability.py": {"atomic-write"},
    "core/raw_pallas.py": {"kernel-gate"},
    "suppression_no_reason.py": {"blocking-under-lock",
                                 "suppression-hygiene"},
}


def _fixture_name(path: str) -> str:
    parts = pathlib.PurePath(path).parts
    return "/".join(parts[parts.index("analysis") + 1:])


def test_fixture_package_seeds_every_rule():
    res = analysis.analyze(paths=[FIXTURES])
    got = {}
    for f in res.findings:
        got.setdefault(_fixture_name(f.path), set()).add(f.rule)
    assert got == EXPECTED_FIXTURE_RULES
    # every registered rule is exercised by at least one fixture — a
    # rule that stops firing on its own seeded violation fails HERE,
    # not silently in some future review
    flagged = set().union(*got.values())
    assert set(analysis.all_rules()) <= flagged


# ---------------------------------------------------------------------------
# Suppression grammar
# ---------------------------------------------------------------------------

_SLEEP_UNDER_LOCK = (
    "import threading\n"
    "import time\n"
    "_lock = threading.Lock()\n"
    "def tick():\n"
    "    with _lock:\n"
    "        time.sleep(0.1){comment}\n"
)


def _run(source: str, rule_ids=None, rel: str = "mem.py"):
    src = framework.SourceFile.from_source(source, rel=rel)
    return analysis.analyze_sources([src], rule_ids=rule_ids)


def test_justified_suppression_suppresses():
    res = _run(_SLEEP_UNDER_LOCK.format(
        comment="  # sparkdl: allow(blocking-under-lock): test lock is "
                "single-threaded"))
    assert not res.findings
    assert len(res.suppressed) == 1
    finding, why = res.suppressed[0]
    assert finding.rule == "blocking-under-lock"
    assert why == "test lock is single-threaded"


def test_wrong_rule_name_does_not_suppress():
    res = _run(_SLEEP_UNDER_LOCK.format(
        comment="  # sparkdl: allow(broad-retry): wrong rule entirely"))
    assert [f.rule for f in res.findings] == ["blocking-under-lock"]
    assert not res.suppressed


def test_missing_justification_does_not_suppress_and_is_flagged():
    res = _run(_SLEEP_UNDER_LOCK.format(
        comment="  # sparkdl: allow(blocking-under-lock)"))
    assert {f.rule for f in res.findings} == {"blocking-under-lock",
                                             "suppression-hygiene"}


def test_unknown_rule_in_suppression_is_flagged():
    res = _run("x = 1  # sparkdl: allow(no-such-rule): because\n")
    assert [f.rule for f in res.findings] == ["suppression-hygiene"]
    assert "no-such-rule" in res.findings[0].message


def test_unrecognized_directive_is_flagged():
    res = _run("x = 1  # sparkdl: alow(broad-retry): typo'd verb\n")
    assert [f.rule for f in res.findings] == ["suppression-hygiene"]


def test_stacked_comment_only_directives_target_the_same_statement():
    """Comment-only directives skip over further comment lines to the
    next CODE line — a directive stacked above another comment must not
    silently target the comment and suppress nothing."""
    source = (
        "import threading\n"
        "import time\n"
        "_lock = threading.Lock()\n"
        "def t():\n"
        "    with _lock:\n"
        "        # sparkdl: allow(blocking-under-lock): io is the point\n"
        "        # sparkdl: allow(unguarded-shared-write): stacked, inert\n"
        "        # an ordinary explanatory comment in between\n"
        "        time.sleep(0.1)\n"
    )
    src = framework.SourceFile.from_source(source)
    assert [s.target for s in src.suppressions()] == [9, 9]
    res = analysis.analyze_sources([src])
    assert not res.findings
    assert len(res.suppressed) == 1  # the sleep; the second is inert


def test_comment_only_line_suppresses_the_next_line():
    source = (
        "import threading\n"
        "import time\n"
        "_lock = threading.Lock()\n"
        "def tick():\n"
        "    with _lock:\n"
        "        # sparkdl: allow(blocking-under-lock): multi-line "
        "statement below\n"
        "        time.sleep(\n"
        "            0.1)\n"
    )
    res = _run(source)
    assert not res.findings
    assert len(res.suppressed) == 1


def test_docstring_mention_is_not_a_directive():
    """Only COMMENT tokens parse as directives — prose/docstrings
    describing the syntax must not trip hygiene (or suppress)."""
    source = (
        '"""Write `# sparkdl: allow(rule): why` to suppress.\n'
        "\n"
        "Also mentions # sparkdl: allow(broad-retry) mid-text.\n"
        '"""\n'
        "x = 1\n"
    )
    res = _run(source)
    assert not res.findings


# ---------------------------------------------------------------------------
# Baseline round-trip
# ---------------------------------------------------------------------------


def test_baseline_round_trip(tmp_path):
    res = analysis.analyze(paths=[FIXTURES])
    assert res.findings
    path = tmp_path / "baseline.json"
    grandfatherable_in = [f for f in res.findings
                          if f.rule != "suppression-hygiene"]
    baseline_mod.Baseline.from_findings(grandfatherable_in).save(path)

    loaded = baseline_mod.Baseline.load(path)
    res2 = analysis.analyze(paths=[FIXTURES], baseline=loaded)
    # everything grandfatherable is absorbed; hygiene findings are
    # NEVER baselineable (a one-command bypass of the justification
    # requirement otherwise) and keep firing
    assert {f.rule for f in res2.findings} == {"suppression-hygiene"}
    grandfatherable = [f for f in res.findings
                       if f.rule != "suppression-hygiene"]
    assert len(res2.baselined) == len(grandfatherable)
    assert not res2.stale_baseline


def test_baseline_matching_survives_line_shifts(tmp_path):
    """Messages embed 'acquired line N' context; the baseline key
    normalizes those so an unrelated edit shifting the file doesn't
    churn the baseline."""
    bad = (FIXTURES / "blocking_under_lock.py").read_text()
    res = analysis.analyze_sources(
        [framework.SourceFile.from_source(bad, rel="shifty.py")],
        rule_ids=["blocking-under-lock"])
    bl = baseline_mod.Baseline.from_findings(res.findings)
    shifted = "# a new leading comment shifts every line\n" + bad
    res2 = analysis.analyze_sources(
        [framework.SourceFile.from_source(shifted, rel="shifty.py")],
        rule_ids=["blocking-under-lock"], baseline=bl)
    assert not res2.findings
    assert len(res2.baselined) == 1
    assert not res2.stale_baseline


def test_baseline_stale_entries_are_surfaced(tmp_path):
    res = analysis.analyze(paths=[FIXTURES])
    stale_entry = {"rule": "broad-retry", "path": "deleted_file.py",
                   "message": "no longer exists"}
    bl = baseline_mod.Baseline(
        [f.as_dict() for f in res.findings
         if f.rule != "suppression-hygiene"] + [stale_entry])
    res2 = analysis.analyze(paths=[FIXTURES], baseline=bl)
    assert {f.rule for f in res2.findings} == {"suppression-hygiene"}
    assert res2.stale_baseline == [stale_entry]


def test_baseline_load_missing_file_is_empty(tmp_path):
    bl = baseline_mod.Baseline.load(tmp_path / "absent.json")
    assert bl.entries == []


# ---------------------------------------------------------------------------
# CLI: exit codes + --json schema
# ---------------------------------------------------------------------------


def test_cli_exit_0_on_clean_tree(capsys):
    assert cli.main([str(PACKAGE)]) == 0
    out = capsys.readouterr().out
    assert "0 finding(s)" in out


def test_cli_exit_1_on_findings(capsys):
    assert cli.main([str(FIXTURES), "--no-baseline"]) == 1
    assert "[broad-retry]" in capsys.readouterr().out


def test_cli_exit_2_on_unknown_rule(capsys):
    assert cli.main([str(FIXTURES), "--rule", "no-such-rule"]) == 2
    assert "no-such-rule" in capsys.readouterr().err


def test_cli_exit_2_on_missing_path(capsys):
    assert cli.main(["/no/such/path/anywhere"]) == 2


def test_cli_json_schema(capsys):
    assert cli.main([str(FIXTURES), "--json", "--no-baseline"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == 1
    assert set(doc) >= {"version", "rules", "files", "findings",
                        "suppressed", "counts", "stale_baseline"}
    assert doc["counts"]["findings"] == len(doc["findings"]) > 0
    for f in doc["findings"]:
        assert set(f) == {"rule", "path", "line", "message"}
        assert isinstance(f["line"], int)
    assert set(doc["rules"]) == set(analysis.all_rules()) | {
        framework.SUPPRESSION_HYGIENE}


def test_cli_rule_filter(capsys):
    assert cli.main([str(FIXTURES), "--rule", "broad-retry",
                     "--json", "--no-baseline"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert {f["rule"] for f in doc["findings"]} == {"broad-retry"}


def test_cli_list_rules(capsys):
    assert cli.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in analysis.all_rules():
        assert rule_id in out


def test_cli_write_baseline(tmp_path, capsys):
    # a hygiene-free target: those findings are never grandfatherable
    target = str(FIXTURES / "broad_retry.py")
    path = tmp_path / "bl.json"
    assert cli.main([target, "--baseline", str(path),
                     "--write-baseline"]) == 0
    assert cli.main([target, "--baseline", str(path)]) == 0


def test_cli_write_baseline_is_idempotent(tmp_path, capsys):
    """Regenerating must not absorb its own entries: a second
    --write-baseline run writes the SAME file, and the tree still
    passes against it (the write path ignores the loaded baseline)."""
    target = str(FIXTURES / "broad_retry.py")
    path = tmp_path / "bl.json"
    assert cli.main([target, "--baseline", str(path),
                     "--write-baseline"]) == 0
    first = path.read_text()
    assert json.loads(first)["entries"]
    assert cli.main([target, "--baseline", str(path),
                     "--write-baseline"]) == 0
    assert path.read_text() == first
    assert cli.main([target, "--baseline", str(path)]) == 0


def test_cli_write_baseline_excludes_hygiene_findings(tmp_path, capsys):
    """--write-baseline must not grandfather suppression-hygiene: an
    unjustified directive stays a failure even after regenerating."""
    path = tmp_path / "bl.json"
    assert cli.main([str(FIXTURES / "suppression_no_reason.py"),
                     "--baseline", str(path), "--write-baseline"]) == 0
    entries = json.loads(path.read_text())["entries"]
    assert all(e["rule"] != "suppression-hygiene" for e in entries)
    assert cli.main([str(FIXTURES / "suppression_no_reason.py"),
                     "--baseline", str(path)]) == 1


# ---------------------------------------------------------------------------
# Concurrency-rule self-tests: seed each hazard through the framework
# (the acceptance-criteria quartet, plus resolution edge cases)
# ---------------------------------------------------------------------------


def test_lock_order_cycle_is_caught():
    source = (FIXTURES / "lock_order_cycle.py").read_text()
    res = _run(source, rule_ids=["lock-order"])
    assert len(res.findings) == 1
    msg = res.findings[0].message
    assert "cycle" in msg and "TwoLocks._a" in msg and "TwoLocks._b" in msg


def test_lock_order_flags_plain_lock_reacquired_through_helper():
    """Interprocedural self-deadlock: a method holding a plain Lock
    calls a helper that takes the same Lock again."""
    source = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._n = 0\n"
        "    def outer(self):\n"
        "        with self._lock:\n"
        "            self._helper()\n"
        "    def _helper(self):\n"
        "        with self._lock:\n"
        "            self._n += 1\n"
    )
    res = _run(source, rule_ids=["lock-order"])
    assert len(res.findings) == 1
    assert "re-acquired" in res.findings[0].message


def test_lock_order_rlock_reacquisition_is_fine():
    source = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.RLock()\n"
        "    def outer(self):\n"
        "        with self._lock:\n"
        "            with self._lock:\n"
        "                pass\n"
    )
    assert not _run(source, rule_ids=["lock-order"]).findings


def test_lock_order_nonblocking_acquire_is_not_an_edge():
    """acquire(blocking=False) cannot deadlock — the executor's stale
    sweep relies on exactly this exemption."""
    source = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n"
        "    def forward(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n"
        "    def sweep(self):\n"
        "        with self._b:\n"
        "            if self._a.acquire(blocking=False):\n"
        "                self._a.release()\n"
    )
    assert not _run(source, rule_ids=["lock-order"]).findings


def test_wait_holding_foreign_lock_is_caught():
    source = (FIXTURES / "wait_foreign_lock.py").read_text()
    res = _run(source, rule_ids=["wait-holding-lock"])
    assert len(res.findings) == 1
    assert "Waiter._lock" in res.findings[0].message


def test_wait_under_own_lock_only_is_fine():
    source = (
        "import threading\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._cond = threading.Condition()\n"
        "        self.ready = False\n"
        "    def block(self):\n"
        "        with self._cond:\n"
        "            while not self.ready:\n"
        "                self._cond.wait()\n"
    )
    assert not _run(source, rule_ids=["wait-holding-lock"]).findings


def test_blocking_under_lock_is_caught_directly():
    res = _run((FIXTURES / "blocking_under_lock.py").read_text(),
               rule_ids=["blocking-under-lock"])
    assert len(res.findings) == 1
    assert "time.sleep" in res.findings[0].message


def test_blocking_under_lock_propagates_through_helper_calls():
    """The exporter shape: the lock is taken in one method, the file
    write lives in a helper — the finding lands on the write."""
    source = (
        "import threading\n"
        "class E:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def tick(self):\n"
        "        with self._lock:\n"
        "            self._flush()\n"
        "    def _flush(self):\n"
        "        with open('/tmp/x', 'w') as f:\n"
        "            f.write('snapshot')\n"
    )
    res = _run(source, rule_ids=["blocking-under-lock"])
    lines = sorted(f.line for f in res.findings)
    assert lines == [9, 10]  # open() and .write(), not the call site
    assert all("E._lock" in f.message for f in res.findings)


def test_unguarded_shared_write_is_caught_and_init_exempt():
    res = _run((FIXTURES / "unguarded_write.py").read_text(),
               rule_ids=["unguarded-shared-write"])
    assert len(res.findings) == 1
    assert "RacyCounter.bump" in res.findings[0].message
    # __init__'s writes and the guarded read stayed clean: only line 12
    assert res.findings[0].line == 12


def test_guarded_write_and_lockless_class_are_fine():
    source = (
        "import threading\n"
        "class Guarded:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._n = 0\n"
        "    def bump(self):\n"
        "        with self._lock:\n"
        "            self._n += 1\n"
        "class NoLocks:\n"
        "    def set(self, v):\n"
        "        self._v = v\n"  # no lock owned: out of scope
    )
    assert not _run(source, rule_ids=["unguarded-shared-write"]).findings


def test_thread_lifecycle_catches_unnamed_and_unjoinable():
    res = _run((FIXTURES / "thread_lifecycle.py").read_text(),
               rule_ids=["thread-lifecycle"])
    msgs = " | ".join(f.message for f in res.findings)
    assert "without name=" in msgs
    assert "join" in msgs


def test_thread_lifecycle_named_and_joined_is_fine():
    source = (
        "import threading\n"
        "class P:\n"
        "    def start(self):\n"
        "        self._t = threading.Thread(target=self.run,\n"
        "                                   name='sparkdl-worker')\n"
        "    def close(self):\n"
        "        self._t.join()\n"
    )
    assert not _run(source, rule_ids=["thread-lifecycle"]).findings


def test_process_lifecycle_catches_unnamed_and_unreapable():
    """The multiprocessing extension (ISSUE 9): an unnamed, non-daemon
    Process in a join-free module is flagged on both counts."""
    res = _run((FIXTURES / "process_lifecycle.py").read_text(),
               rule_ids=["thread-lifecycle"])
    msgs = " | ".join(f.message for f in res.findings)
    assert "multiprocessing.Process" in msgs
    assert "without name=" in msgs
    assert "join" in msgs


def test_process_lifecycle_named_daemon_via_get_context_is_fine():
    """The decode pool's exact shape: a module-level get_context(...)
    variable's .Process(...) with name= and daemon=True, joined in
    close() — clean on every count."""
    source = (
        "import multiprocessing\n"
        "_CTX = multiprocessing.get_context('spawn')\n"
        "class Pool:\n"
        "    def spawn(self, i):\n"
        "        p = _CTX.Process(target=print, name=f'sparkdl-{i}',\n"
        "                         daemon=True)\n"
        "        p.start()\n"
        "        return p\n"
        "    def close(self, p):\n"
        "        p.join()\n"
    )
    assert not _run(source, rule_ids=["thread-lifecycle"]).findings


def test_process_lifecycle_daemon_without_join_is_fine():
    """daemon=True satisfies the reap requirement on its own (the
    interpreter kills daemonic workers at exit); name= is still
    required."""
    source = (
        "import multiprocessing as mp\n"
        "def launch(fn):\n"
        "    p = mp.Process(target=fn, name='sparkdl-w', daemon=True)\n"
        "    p.start()\n"
        "    return p\n"
    )
    assert not _run(source, rule_ids=["thread-lifecycle"]).findings


def test_process_lifecycle_local_get_context_resolves():
    """A get_context(...) bound to a LOCAL inside the function is a
    process factory too."""
    source = (
        "import multiprocessing\n"
        "def launch(fn):\n"
        "    ctx = multiprocessing.get_context('spawn')\n"
        "    p = ctx.Process(target=fn)\n"
        "    p.start()\n"
        "    return p\n"
    )
    res = _run(source, rule_ids=["thread-lifecycle"])
    msgs = " | ".join(f.message for f in res.findings)
    assert "multiprocessing.Process" in msgs and "without name=" in msgs


def test_process_handle_lookup_is_not_a_process_factory():
    """psutil-style `X.Process(pid)` HANDLE lookups on arbitrary
    receivers create nothing and must not be flagged."""
    source = (
        "import psutil\n"
        "def rss(pid):\n"
        "    return psutil.Process(pid).memory_info().rss\n"
    )
    assert not _run(source, rule_ids=["thread-lifecycle"]).findings


def test_same_class_name_in_two_modules_is_not_a_phantom_cycle():
    """Lock identities are module-qualified: two unrelated `Worker`
    classes nesting their locks in opposite orders are four distinct
    locks, not a deadlock."""
    a = (
        "import threading\n"
        "class Worker:\n"
        "    def __init__(self):\n"
        "        self._x = threading.Lock()\n"
        "        self._y = threading.Lock()\n"
        "    def go(self):\n"
        "        with self._x:\n"
        "            with self._y:\n"
        "                pass\n"
    )
    b = a.replace("with self._x:", "with self._TMP:") \
         .replace("with self._y:", "with self._x:") \
         .replace("with self._TMP:", "with self._y:")
    res = analysis.analyze_sources(
        [framework.SourceFile.from_source(a, rel="mod_a.py"),
         framework.SourceFile.from_source(b, rel="mod_b.py")],
        rule_ids=["lock-order"])
    assert not res.findings


def test_thread_lifecycle_sees_module_level_threads():
    """An import-time `threading.Thread(...)` (the shape most likely to
    leak) is not invisible just because it lives outside any def."""
    source = (
        "import threading\n"
        "_t = threading.Thread(target=print)\n"
        "_t.start()\n"
    )
    res = _run(source, rule_ids=["thread-lifecycle"])
    msgs = " | ".join(f.message for f in res.findings)
    assert "without name=" in msgs and "join" in msgs


def test_str_join_is_not_a_thread_join():
    """`sep.join(items)` on a non-literal receiver is str.join: neither
    a blocking call under a lock nor a module join path."""
    source = (
        "import threading\n"
        "_lock = threading.Lock()\n"
        "def fmt(sep, items):\n"
        "    with _lock:\n"
        "        return sep.join(items)\n"
        "def leak(fn):\n"
        "    threading.Thread(target=fn, name='sparkdl-x').start()\n"
    )
    res = _run(source, rule_ids=["blocking-under-lock",
                                 "thread-lifecycle"])
    # no blocking finding for str.join; the named thread still lacks a
    # REAL join path (sep.join must not satisfy it)
    assert [f.rule for f in res.findings] == ["thread-lifecycle"]
    assert "join" in res.findings[0].message


def test_blank_line_between_directive_and_statement_still_suppresses():
    source = (
        "import threading\n"
        "import time\n"
        "_lock = threading.Lock()\n"
        "def t():\n"
        "    with _lock:\n"
        "        # sparkdl: allow(blocking-under-lock): spaced out\n"
        "\n"
        "        time.sleep(0.1)\n"
    )
    res = _run(source)
    assert not res.findings
    assert len(res.suppressed) == 1


def test_blocking_reachability_survives_call_cycles():
    """Mutually-recursive helpers: the blocking site must still be
    reachable from a locked caller regardless of traversal order (the
    closure is a fixpoint, not a memoized DFS that caches partial
    results for cycle participants)."""
    source = (
        "import threading\n"
        "import time\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def a(self, n):\n"
        "        time.sleep(0.1)\n"
        "        if n:\n"
        "            self.b(n - 1)\n"
        "    def b(self, n):\n"
        "        if n:\n"
        "            self.a(n - 1)\n"
        "    def locked_entry(self):\n"
        "        with self._lock:\n"
        "            self.b(3)\n"
    )
    res = _run(source, rule_ids=["blocking-under-lock"])
    assert len(res.findings) == 1
    assert res.findings[0].line == 7  # the sleep, via b -> a


def test_annotated_param_lock_resolution():
    """The executor idiom: a method of one class locks another class's
    condition through an annotated parameter."""
    source = (
        "import threading\n"
        "import time\n"
        "class State:\n"
        "    def __init__(self):\n"
        "        self.cond = threading.Condition()\n"
        "class Service:\n"
        "    def drain(self, state: State):\n"
        "        with state.cond:\n"
        "            time.sleep(0.5)\n"
    )
    res = _run(source, rule_ids=["blocking-under-lock"])
    assert len(res.findings) == 1
    assert "State.cond" in res.findings[0].message
