"""Fixture: raw Pallas usage outside core/kernels.py (kernel-gate).

Both defects ship un-auditioned device code: a bare ``pallas_call``
launch bypasses the accept-if-faster registry entirely, and a direct
call to a ``core.kernels`` raw builder skips the adopted-verdict check
(the route_* entry points are the only sanctioned way in).
"""

from jax.experimental import pallas as pl

from sparkdl_tpu.core import kernels


def launches_raw_pallas(kernel, x, out_shape):
    return pl.pallas_call(kernel, out_shape=out_shape)(x)


def calls_raw_builder(x, dw9, pw, scale, shift):
    return kernels.sep2d(x, dw9, pw, scale, shift)
