"""Seeded violation: blind broad-except retry loop (broad-retry)."""


def flaky(op):
    last = None
    for _attempt in range(3):
        try:
            return op()
        except Exception as e:
            last = e
    return last
