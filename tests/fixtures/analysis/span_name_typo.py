"""Seeded violation: typo'd span name (span-names)."""

from sparkdl_tpu.core import profiling


def run(step):
    with profiling.annotate('sparkdl.train_stepp'):
        return step()
