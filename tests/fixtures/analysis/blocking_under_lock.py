"""Seeded violation: time.sleep under a held lock (blocking-under-lock)."""

import threading
import time

_lock = threading.Lock()


def tick():
    with _lock:
        time.sleep(0.1)
