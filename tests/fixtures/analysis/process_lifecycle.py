"""Seeded violation: unnamed, unreapable worker process
(thread-lifecycle, multiprocessing.Process extension)."""

import multiprocessing


def launch(fn):
    p = multiprocessing.Process(target=fn)
    p.start()
    return p
