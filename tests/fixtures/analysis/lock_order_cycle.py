"""Seeded violation: two locks taken in opposite orders (lock-order)."""

import threading


class TwoLocks:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._n = 0

    def forward(self):
        with self._a:
            with self._b:
                self._n += 1

    def backward(self):
        with self._b:
            with self._a:
                self._n -= 1
