"""Seeded violation: cond.wait holding another lock (wait-holding-lock)."""

import threading


class Waiter:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition()
        self._ready = False

    def stall(self):
        with self._lock:
            with self._cond:
                while not self._ready:
                    self._cond.wait(timeout=1.0)
