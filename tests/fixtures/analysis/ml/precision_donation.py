"""Seeded violation: per-call-site precision/donation decisions on the
featurize route (executor-choke-point; the `ml/` path segment puts this
in scope) — with_dtype and jitted(donate_batch=...) must enter through
EngineConfig at the executor choke point, never per call site."""


def featurize_partition(model, batch):
    fast = model.with_dtype("bfloat16")
    fn = fast.jitted(donate_batch=True)
    return fn(batch)
