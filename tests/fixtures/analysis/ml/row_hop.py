"""Seeded violation: per-row hops over image/tensor columns on the
data plane (columnar-hot-path; the `ml/` path segment puts this in
scope) — a `.to_pylist()` materialization and a per-row
`imageArrayToStruct` loop."""

import pyarrow as pa

from sparkdl_tpu.image.imageIO import imageArrayToStruct, imageSchema


def stage_partition(batch):
    col = batch.column(0)
    structs = col.to_pylist()
    return [s for s in structs if s is not None]


def rebuild_column(arrays, origins):
    values = [imageArrayToStruct(a, origin=o)
              for a, o in zip(arrays, origins)]
    return pa.array(values, type=imageSchema)
