"""Seeded violation: direct device entry on the featurize route
(executor-choke-point; the `ml/` path segment puts this in scope)."""


def apply_partition(model, batch, mesh):
    fn = model.jitted(mesh=mesh)
    del fn
    return model.apply_batch(batch, batch_size=64)
