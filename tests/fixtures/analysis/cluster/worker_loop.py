"""Seeded violations for the cluster inference plane: a worker process
spawned unnamed and never joined (thread-lifecycle — a died-silently
cluster worker is undebuggable without a name, unreapable without a
join path), and a worker loop entering the device directly instead of
through its per-process executor (executor-choke-point; the `cluster/`
path segment puts this in scope — bypassing the executor loses
coalescing, admission control and the compiled-fn cache the per-worker
stack exists to provide)."""

import multiprocessing


def spawn_worker(loop):
    proc = multiprocessing.Process(target=loop)
    proc.start()
    return proc


def run_chain(model, batch):
    return model.apply_batch(batch, batch_size=32)
