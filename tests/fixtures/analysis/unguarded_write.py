"""Seeded violation: self._* store with no lock (unguarded-shared-write)."""

import threading


class RacyCounter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def bump(self):
        self._n += 1

    def read(self):
        with self._lock:
            return self._n
