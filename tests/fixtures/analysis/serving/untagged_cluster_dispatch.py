"""Seeded violation: the cluster serving router's wire dispatch called
without a tenant tag (tenant-tag; ``submit_predict`` is a serving-plane
dispatch entry point — a routed predict that drops the tag burns the
default lane's fair-queueing quota on the WORKER, invisibly to the
coordinator's per-tenant series)."""


def failover_readmit(router, wid, call):
    return router.submit_predict(wid, call, crash=False)
