"""Seeded violation: the online serving plane bypassing the executor
(executor-choke-point; the `serving/` path segment puts this in scope —
a ModelServer launching via apply_batch would silently lose coalescing,
priority lanes, admission control and the breaker for online traffic)."""


def predict_row(model, row):
    return model.apply_batch(row[None], batch_size=1)
