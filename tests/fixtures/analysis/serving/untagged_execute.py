"""Seeded violation: a serving-plane request entering the executor
without a tenant tag (tenant-tag; the `serving/` path segment puts this
in scope — an untagged online request burns the shared default lane's
deficit-round-robin quota, so one client's flood starves every other
untagged client with no per-tenant series to show it)."""

from sparkdl_tpu.core import executor


def predict_row(model, batch):
    return executor.execute(model, batch, batch_size=1)
