"""Seeded violation: blocking fetch in the step loop
(blocking-fetch-in-fit)."""


class Trainer:
    def fit(self, state, batches):
        def sync(st):
            return int(st.step)  # helper definition: exempt

        for x, y in batches:
            state, metrics = self.step(state, x, y)
            step_n = int(state.step)  # the per-step blocking fetch
            self.log(step_n, metrics)
        return state
