"""Seeded violation: typo'd SLO metric name (slo-metrics)."""

from sparkdl_tpu.core.slo import SLORule

RULES = [
    SLORule('queue-wait', metric='sparkdl.executor.queue_wait_ss',
            window_s=30.0, threshold=1.0),
]
