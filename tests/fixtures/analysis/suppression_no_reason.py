"""Seeded violation: a justification-less suppression neither
suppresses (blocking-under-lock still fires) nor passes hygiene
(suppression-hygiene fires on the directive)."""

import threading
import time

_lock = threading.Lock()


def tick():
    with _lock:
        time.sleep(0.1)  # sparkdl: allow(blocking-under-lock)
