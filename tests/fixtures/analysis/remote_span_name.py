"""Seeded violation: non-canonical span name shipped across a process
boundary (span-names) — the adopting tracer would reject it and the
span would vanish from the merged timeline."""

from sparkdl_tpu.core import telemetry


def ship(conn, t0_ns, t1_ns):
    conn.send(telemetry.remote_span('sparkdl.decode_chunkk',
                                    t0_ns, t1_ns))
