"""Seeded violation: typo'd metric key read out of a federated
windowed-snapshot section (slo-metrics). The lookup silently returns
None forever — the autoscaler here would simply never scale."""


def cluster_queue_pressure(view):
    snap = view.window_snapshot(30.0)
    return snap["histograms"].get("sparkdl.executor.queue_wait_ss")
