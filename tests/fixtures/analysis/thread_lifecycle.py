"""Seeded violation: unnamed, unjoinable thread (thread-lifecycle)."""

import threading


def fire_and_forget(fn):
    t = threading.Thread(target=fn, daemon=True)
    t.start()
    return t
