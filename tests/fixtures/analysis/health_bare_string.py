"""Seeded violation: bare-string health event (health-constants)."""

from sparkdl_tpu.core import health


def run(partition):
    health.record('task_retried', partition=partition)
