"""Seeded violation: in-place write of durable state (atomic-write).

The filename matters: the rule scopes to state-persisting modules
(durability.py, checkpoint.py, baseline.py, telemetry.py).
"""

import json


def commit(path, records):
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
