"""Online serving plane (ISSUE 13 tentpole): row-level requests through
the executor choke point, versioned hot-swap with zero dropped /
double-served requests, deterministic shadow traffic, SLO-aware
admission, and the executor_idle_retire_s knob."""

import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from sparkdl_tpu.core import executor, health, slo, telemetry
from sparkdl_tpu.core.health import HealthMonitor
from sparkdl_tpu.core.model_function import ModelFunction, TensorSpec
from sparkdl_tpu.core.telemetry import Telemetry
from sparkdl_tpu.engine.dataframe import EngineConfig
from sparkdl_tpu.serving import (
    ModelRegistry,
    ModelServer,
    ResidencyManager,
    ServingOverloaded,
)

_ELEMENT = (6,)
_FEATURES = 3


@pytest.fixture(autouse=True)
def _fresh_executor():
    saved = EngineConfig.snapshot()
    executor.reset()
    yield
    executor.reset()
    EngineConfig.restore(saved)


def _model(scale: float, name: str = "served") -> ModelFunction:
    rng = np.random.default_rng(7)
    w = jnp.asarray((rng.normal(size=(_ELEMENT[0], _FEATURES)) * scale)
                    .astype(np.float32))
    return ModelFunction(lambda vs, x: jnp.tanh(x @ vs), w,
                         TensorSpec((None,) + _ELEMENT, "float32"),
                         name=name)


def _reference(model: ModelFunction, rows: np.ndarray) -> np.ndarray:
    """Ground truth computed WITHOUT the serving stack (fp32 conftest
    pin makes the served outputs bit-identical to this)."""
    return np.asarray(jnp.tanh(jnp.asarray(rows) @ model.variables))


def _serving_stack(**server_kw):
    reg = ModelRegistry()
    return reg, ModelServer(reg, **server_kw)


# ---------------------------------------------------------------------------
# Request API basics
# ---------------------------------------------------------------------------


def test_single_row_and_small_batch_roundtrip(rng):
    reg, srv = _serving_stack()
    m = _model(1.0)
    reg.deploy("clf", "v1", model=m)
    row = rng.normal(size=_ELEMENT).astype(np.float32)
    got = srv.predict("clf", row)
    assert got.version == "v1"
    assert got.output.shape == (_FEATURES,)
    np.testing.assert_array_equal(got.output, _reference(m, row[None])[0])
    batch = rng.normal(size=(5,) + _ELEMENT).astype(np.float32)
    got = srv.predict("clf", batch)
    assert np.asarray(got.output).shape == (5, _FEATURES)
    np.testing.assert_array_equal(got.output, _reference(m, batch))


def test_predict_unknown_model_raises():
    _, srv = _serving_stack()
    with pytest.raises(KeyError, match="no model named"):
        srv.predict("ghost", np.zeros(_ELEMENT, np.float32))


def test_predict_records_serving_metrics(rng):
    reg, srv = _serving_stack()
    reg.deploy("clf", "v1", model=_model(1.0))
    with Telemetry("serving-test", window_s=30.0) as tel:
        srv.predict("clf", rng.normal(size=_ELEMENT).astype(np.float32))
        hist = tel.metrics.histogram(telemetry.M_SERVING_REQUEST_S)
        assert hist.count == 1
        per_model = tel.metrics.histogram(
            telemetry.serving_request_metric("clf"))
        assert per_model.count == 1


def test_deadline_propagates_to_executor(rng):
    """An already-expired deadline is shed AT admission inside the
    executor — the serving deadline_ms parameter reaches the device
    service, it isn't decorative."""
    from sparkdl_tpu.core import resilience

    reg, srv = _serving_stack()
    reg.deploy("clf", "v1", model=_model(1.0))
    with pytest.raises(resilience.DeadlineExceeded):
        srv.predict("clf", rng.normal(size=_ELEMENT).astype(np.float32),
                    deadline_ms=0.0)


# ---------------------------------------------------------------------------
# Versioned registry: deploy / shadow / cutover / rollback
# ---------------------------------------------------------------------------


def test_deploy_versions_are_immutable():
    reg, _ = _serving_stack()
    reg.deploy("clf", "v1", model=_model(1.0))
    with pytest.raises(ValueError, match="already deployed"):
        reg.deploy("clf", "v1", model=_model(2.0))


def test_shadow_fraction_is_deterministic(rng):
    """fraction=0.25 mirrors EXACTLY every 4th request — accumulator,
    not RNG, so replay runs see the same shadow set."""
    reg, srv = _serving_stack()
    reg.deploy("clf", "v1", model=_model(1.0))
    reg.deploy("clf", "v2", model=_model(2.0))
    reg.shadow("clf", "v2", fraction=0.25)
    rows = rng.normal(size=(8,) + _ELEMENT).astype(np.float32)
    with HealthMonitor("shadow") as mon:
        flags = [srv.predict("clf", rows[i]).shadowed for i in range(8)]
    assert flags == [False, False, False, True] * 2
    assert mon.count(health.SERVING_SHADOW_COMPARED) == 2


def test_shadow_responses_come_from_active_and_divergence_recorded(rng):
    reg, srv = _serving_stack()
    v1, v2 = _model(1.0), _model(2.0)
    reg.deploy("clf", "v1", model=v1)
    reg.deploy("clf", "v2", model=v2)
    reg.shadow("clf", "v2", fraction=1.0)
    row = rng.normal(size=_ELEMENT).astype(np.float32)
    with Telemetry("shadow-div", window_s=30.0) as tel:
        with HealthMonitor("shadow") as mon:
            got = srv.predict("clf", row)
        assert got.version == "v1"  # the answer is ALWAYS the active's
        np.testing.assert_array_equal(got.output,
                                      _reference(v1, row[None])[0])
        div = tel.metrics.histogram(
            telemetry.M_SERVING_SHADOW_DIVERGENCE)
        assert div.count == 1
    events = mon.events(health.SERVING_SHADOW_COMPARED)
    assert len(events) == 1
    expected_div = float(np.max(np.abs(
        _reference(v1, row[None]) - _reference(v2, row[None]))))
    assert events[0]["divergence"] == pytest.approx(expected_div)


def test_shadow_failure_never_fails_the_request(rng):
    reg, srv = _serving_stack()
    v1 = _model(1.0)
    reg.deploy("clf", "v1", model=v1)

    def bad_loader():
        raise RuntimeError("candidate model is broken")

    reg.deploy("clf", "v2", loader=bad_loader)
    reg.shadow("clf", "v2", fraction=1.0)
    row = rng.normal(size=_ELEMENT).astype(np.float32)
    with HealthMonitor("shadow-err") as mon:
        got = srv.predict("clf", row)
    np.testing.assert_array_equal(got.output, _reference(v1, row[None])[0])
    assert mon.count(health.SERVING_SHADOW_ERROR) == 1


def test_shadow_validation():
    reg, _ = _serving_stack()
    reg.deploy("clf", "v1", model=_model(1.0))
    with pytest.raises(KeyError, match="no version"):
        reg.shadow("clf", "v9")
    with pytest.raises(ValueError, match="active version"):
        reg.shadow("clf", "v1")
    reg.deploy("clf", "v2", model=_model(2.0))
    with pytest.raises(ValueError, match="fraction"):
        reg.shadow("clf", "v2", fraction=1.5)


def test_hot_swap_zero_dropped_zero_double_served_under_load(rng):
    """THE acceptance test: a v1->v2 cutover lands mid-flood. Every
    request gets exactly one answer, that answer is bit-identical to
    the reference output of the version the registry says served it,
    both versions actually serve, shadow comparison records are
    emitted, and rollback (the same primitive) restores v1."""
    reg, srv = _serving_stack()
    v1, v2 = _model(1.0), _model(2.0)
    reg.deploy("clf", "v1", model=v1)
    reg.deploy("clf", "v2", model=v2)
    reg.shadow("clf", "v2", fraction=0.2)  # shadow armed through the swap

    n_threads, per_thread = 4, 25
    rows = rng.normal(size=(n_threads, per_thread) + _ELEMENT
                      ).astype(np.float32)
    ref = {"v1": [_reference(v1, rows[t]) for t in range(n_threads)],
           "v2": [_reference(v2, rows[t]) for t in range(n_threads)]}
    results = [[None] * per_thread for _ in range(n_threads)]
    errors = []
    swap_at = threading.Event()

    def client(t):
        for i in range(per_thread):
            if t == 0 and i == per_thread // 2:
                swap_at.set()
            try:
                results[t][i] = srv.predict("clf", rows[t][i])
            except Exception as e:  # noqa: BLE001 - the test asserts none
                errors.append((t, i, e))

    def swapper():
        swap_at.wait(timeout=30.0)
        reg.cutover("clf", "v2")

    with HealthMonitor("swap") as mon:
        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(n_threads)]
        sw = threading.Thread(target=swapper)
        for th in threads + [sw]:
            th.start()
        for th in threads + [sw]:
            th.join(timeout=60.0)

    assert not errors, f"dropped requests: {errors[:3]}"
    served = {"v1": 0, "v2": 0}
    for t in range(n_threads):
        for i in range(per_thread):
            got = results[t][i]
            assert got is not None, f"request ({t},{i}) never answered"
            served[got.version] += 1
            np.testing.assert_array_equal(
                got.output, ref[got.version][t][i],
                err_msg=f"request ({t},{i}) not bit-identical to its "
                        f"version {got.version}")
    # exactly one answer per request, each from exactly one version
    assert served["v1"] + served["v2"] == n_threads * per_thread
    assert served["v2"] > 0, "cutover never took effect"
    assert mon.count(health.SERVING_CUTOVER) == 1
    assert mon.count(health.SERVING_SHADOW_COMPARED) > 0

    # rollback is the SAME primitive, aimed backwards
    with HealthMonitor("rollback") as mon2:
        assert reg.rollback("clf") == "v2"
    assert reg.active_version("clf") == "v1"
    assert mon2.count(health.SERVING_CUTOVER) == 1
    after = srv.predict("clf", rows[0][0])
    assert after.version == "v1"
    np.testing.assert_array_equal(after.output, ref["v1"][0][0])


def test_rollback_without_history_raises():
    reg, _ = _serving_stack()
    reg.deploy("clf", "v1", model=_model(1.0))
    with pytest.raises(ValueError, match="no previous"):
        reg.rollback("clf")


# ---------------------------------------------------------------------------
# SLO-aware admission
# ---------------------------------------------------------------------------


def _saturate_queue_wait(tel, seconds: float, n: int = 50) -> None:
    for _ in range(n):
        tel.metrics.histogram(telemetry.M_QUEUE_WAIT_S).observe(seconds)


def test_admission_sheds_on_queue_wait_p99_over_budget(rng):
    reg, srv = _serving_stack(slo_window_s=30.0)
    reg.deploy("clf", "v1", model=_model(1.0), latency_target_ms=100.0)
    row = rng.normal(size=_ELEMENT).astype(np.float32)
    with Telemetry("admit", window_s=30.0) as tel:
        srv.predict("clf", row)  # healthy plane admits
        _saturate_queue_wait(tel, 0.2)  # p99 ~200ms > 50ms budget
        with HealthMonitor("shed") as mon:
            with pytest.raises(ServingOverloaded, match="queue-wait p99"):
                srv.predict("clf", row)
        assert mon.count(health.SERVING_SHED) == 1


def test_admission_block_mode_never_sheds(rng):
    reg, srv = _serving_stack(admission="block")
    reg.deploy("clf", "v1", model=_model(1.0), latency_target_ms=100.0)
    row = rng.normal(size=_ELEMENT).astype(np.float32)
    with Telemetry("block", window_s=30.0) as tel:
        _saturate_queue_wait(tel, 0.2)
        got = srv.predict("clf", row)  # admitted; backpressure owns it
    assert got.version == "v1"


def test_admission_without_target_or_telemetry_admits(rng):
    reg, srv = _serving_stack()
    reg.deploy("clf", "v1", model=_model(1.0))  # no latency target
    row = rng.normal(size=_ELEMENT).astype(np.float32)
    assert srv.predict("clf", row).version == "v1"  # no telemetry scope


def test_latency_target_drives_coalesce_window():
    reg, srv = _serving_stack()
    dep = reg.deploy("clf", "v1", model=_model(1.0),
                     latency_target_ms=50.0)
    assert srv._window_ms(dep) == pytest.approx(5.0)  # 10% of target
    loose = reg.deploy("clf", "v2", model=_model(2.0),
                       latency_target_ms=10_000.0)
    assert srv._window_ms(loose) == pytest.approx(20.0)  # capped
    free = reg.deploy("clf2", "v1", model=_model(3.0))
    assert srv._window_ms(free) is None  # adaptive


# ---------------------------------------------------------------------------
# default_serving_rules
# ---------------------------------------------------------------------------


def test_default_serving_rules_per_model_and_shed():
    rules = slo.default_serving_rules({"clf": 0.25, "ranker": 0.5})
    by_name = {r.name: r for r in rules}
    assert "serving_request_p99" in by_name
    assert "serving_shed_rate" in by_name
    clf = by_name["serving_request_p99_clf"]
    assert clf.metric == "sparkdl.serving.request_s.clf"
    assert clf.threshold == 0.25
    assert clf.stat == "p99"
    assert by_name["serving_request_p99_ranker"].threshold == 0.5
    # the dynamic names were declared into the catalog (SLORule
    # construction would have raised otherwise)
    assert "sparkdl.serving.request_s.clf" in \
        telemetry.CANONICAL_METRIC_KINDS


def test_declare_metric_rejects_kind_conflicts():
    telemetry.declare_metric("sparkdl.serving.request_s.tmp_kind",
                             "histogram")
    with pytest.raises(ValueError, match="already declared"):
        telemetry.declare_metric("sparkdl.serving.request_s.tmp_kind",
                                 "counter")
    with pytest.raises(ValueError, match="kind must be"):
        telemetry.declare_metric("sparkdl.serving.x", "timer")


def test_registry_targets_feed_serving_rules():
    reg, _ = _serving_stack()
    reg.deploy("clf", "v1", model=_model(1.0), latency_target_ms=250.0)
    reg.deploy("free", "v1", model=_model(2.0))
    targets = reg.targets()
    assert targets == {"clf": 0.25}
    rules = slo.default_serving_rules(targets)
    assert any(r.name == "serving_request_p99_clf" for r in rules)


def test_cluster_serving_rules_add_failover_rate():
    from sparkdl_tpu.core import health

    rules = slo.cluster_serving_rules({"clf": 0.25})
    by_name = {r.name: r for r in rules}
    # superset of the single-process plane's rules...
    for name in ("serving_request_p99", "serving_shed_rate",
                 "serving_request_p99_clf"):
        assert name in by_name
    # ...plus the sustained-failover watchdog on the health mirror
    fo = by_name["serving_failover_rate"]
    assert fo.metric == telemetry.HEALTH_METRIC_PREFIX \
        + health.SERVING_FAILOVER
    assert fo.stat == "rate_per_s"
    assert fo.threshold == slo.DEFAULT_SERVING_FAILOVER_RATE_PER_S


# ---------------------------------------------------------------------------
# ml/udf resolve through the registry
# ---------------------------------------------------------------------------


def test_transformer_resolves_served_model_name_and_follows_cutover(rng):
    from sparkdl_tpu.engine.dataframe import DataFrame
    from sparkdl_tpu.ml import TPUTransformer
    from sparkdl_tpu.serving.registry import default_registry

    v1, v2 = _model(1.0), _model(2.0)
    reg = default_registry()
    name = "test_transformer_resolves__clf"
    reg.deploy(name, "v1", model=v1)
    rows = rng.normal(size=(6,) + _ELEMENT).astype(np.float32)
    df = DataFrame.fromColumns({"feat": rows}, numPartitions=2)
    tr = TPUTransformer(inputCol="feat", outputCol="out",
                        modelFunction=name, batchSize=4)
    out1 = np.array([r["out"] for r in tr.transform(df).collect()],
                    dtype=np.float32)
    np.testing.assert_array_equal(out1, _reference(v1, rows))
    # a cutover reaches the NEXT transform call — no new transformer
    reg.deploy(name, "v2", model=v2, activate=True)
    out2 = np.array([r["out"] for r in tr.transform(df).collect()],
                    dtype=np.float32)
    np.testing.assert_array_equal(out2, _reference(v2, rows))


# ---------------------------------------------------------------------------
# executor_idle_retire_s knob
# ---------------------------------------------------------------------------


def test_idle_retire_knob_validated_and_snapshotted():
    assert "executor_idle_retire_s" in EngineConfig.snapshot()
    EngineConfig.executor_idle_retire_s = 0.0
    with pytest.raises(ValueError, match="executor_idle_retire_s"):
        EngineConfig.validate()
    EngineConfig.executor_idle_retire_s = -1.0
    with pytest.raises(ValueError, match="executor_idle_retire_s"):
        EngineConfig.validate()
    EngineConfig.executor_idle_retire_s = 0.05
    EngineConfig.validate()


def test_idle_retire_knob_drives_state_retirement(rng):
    """With the knob at 50ms, an idle model's coalescing state (the
    strong reference pinning its weights) is swept well before the old
    hard-coded 5s: solo requests ride the inline fast path (no
    coalescer thread), so retirement happens on the next new-state
    sweep — which the knob now gates."""
    EngineConfig.executor_idle_retire_s = 0.05
    reg, srv = _serving_stack()
    reg.deploy("clf", "v1", model=_model(1.0, name="retire_me"))
    reg.deploy("other", "v1", model=_model(2.0, name="keeper"))
    row = rng.normal(size=_ELEMENT).astype(np.float32)
    srv.predict("clf", row)
    assert [m["model"] for m in executor.status()["models"]] \
        == ["retire_me"]
    time.sleep(0.15)  # > knob; far under the old 5 s constant
    srv.predict("other", row)  # new state -> sweep retires "retire_me"
    names = [m["model"] for m in executor.status()["models"]]
    assert "retire_me" not in names, (
        "idle state survived past executor_idle_retire_s")
    assert "keeper" in names


def test_retire_model_drops_idle_states(rng):
    """DeviceExecutor.retire_model (the residency eviction hook) drops
    an idle model's coalescing state immediately — no sweep needed."""
    reg, srv = _serving_stack()
    m = _model(1.0, name="evictee")
    reg.deploy("clf", "v1", model=m)
    srv.predict("clf", rng.normal(size=_ELEMENT).astype(np.float32))
    assert [s["model"] for s in executor.status()["models"]] \
        == ["evictee"]
    dropped = executor.service().retire_model(
        m, variants=m.device_variants())
    assert dropped >= 1
    assert not executor.status()["models"]


# ---------------------------------------------------------------------------
# AOT bucket-ladder warmup (ISSUE 20): serving_warmup knob
# ---------------------------------------------------------------------------


def test_warmup_armed_deploy_compiles_ladder_before_traffic(rng):
    """Deploy with the knob armed: the full ladder compiles eagerly
    (one WARMUP_COMPLETED, a sparkdl.serving.warmup_s span) and the
    FIRST request then pays zero compile — no sparkdl.compile span."""
    EngineConfig.serving_warmup = True
    reg, srv = _serving_stack()
    m = _model(1.0)
    with Telemetry("warmup") as tel:
        with HealthMonitor("warmup") as mon:
            reg.deploy("clf", "v1", model=m, batch_size=8)
        spans = tel.tracer.spans(name=telemetry.SPAN_SERVING_WARMUP)
    assert len(spans) == 1
    assert mon.count(health.WARMUP_COMPLETED) == 1
    ev = mon.events(health.WARMUP_COMPLETED)[0]
    assert ev["model"] == "clf" and ev["version"] == "v1"
    assert ev["rungs"] >= 1

    row = rng.normal(size=_ELEMENT).astype(np.float32)
    with Telemetry("warmup") as tel:
        got = srv.predict("clf", row)
        assert tel.tracer.spans(name=telemetry.SPAN_COMPILE) == []
    np.testing.assert_array_equal(got.output, _reference(m, row[None])[0])


def test_warmup_off_deploy_stays_lazy(rng):
    """Default (knob off): deploying a loader materializes NOTHING and
    no warmup event fires — first traffic pays the cold start, exactly
    the pre-knob behavior."""
    reg, srv = _serving_stack()
    calls = []

    def loader():
        calls.append(1)
        return _model(1.0)

    row = rng.normal(size=_ELEMENT).astype(np.float32)
    with HealthMonitor("warmup") as mon:
        reg.deploy("clf", "v1", loader=loader, batch_size=8)
        assert calls == [], "deploy materialized a lazy loader"
        srv.predict("clf", row)
    assert calls == [1]
    assert mon.count(health.WARMUP_COMPLETED) == 0


def test_post_cutover_first_request_pays_zero_compile(rng):
    """The dark v2 warms at deploy; after cutover its first live
    request must hit only warmed programs."""
    EngineConfig.serving_warmup = True
    reg, srv = _serving_stack()
    m1, m2 = _model(1.0), _model(-0.5)
    row = rng.normal(size=_ELEMENT).astype(np.float32)
    with HealthMonitor("warmup") as mon:
        reg.deploy("clf", "v1", model=m1, batch_size=8)
        srv.predict("clf", row)
        reg.deploy("clf", "v2", model=m2, batch_size=8)  # dark + warmed
    assert mon.count(health.WARMUP_COMPLETED) == 2
    reg.cutover("clf", "v2")
    with Telemetry("warmup") as tel:
        got = srv.predict("clf", row)
        assert tel.tracer.spans(name=telemetry.SPAN_COMPILE) == []
    assert got.version == "v2"
    np.testing.assert_array_equal(got.output,
                                  _reference(m2, row[None])[0])


def test_eviction_reload_rewarms_ladder(rng):
    """Warmup wraps the LOADER, so a post-eviction residency reload
    pays the ladder again before taking traffic."""
    EngineConfig.serving_warmup = True
    res = ResidencyManager(budget_bytes=10 * 1024)
    reg = ModelRegistry(residency=res)
    srv = ModelServer(reg)
    row = rng.normal(size=_ELEMENT).astype(np.float32)
    with HealthMonitor("warmup") as mon:
        reg.deploy("clf", "v1", loader=lambda: _model(1.0), batch_size=8)
        assert mon.count(health.WARMUP_COMPLETED) == 1
        res.pin("clf", "v1", pinned=False)
        assert res.evict("clf", "v1")
        srv.predict("clf", row)  # cold reload -> the ladder re-warms
    assert mon.count(health.WARMUP_COMPLETED) == 2


def test_warmup_skips_models_without_static_shape(rng):
    """A dynamic element shape has no knowable ladder: warmup skips
    best-effort, deploy and serving still work."""
    EngineConfig.serving_warmup = True
    reg, srv = _serving_stack()
    base = _model(1.0)
    m = ModelFunction(lambda vs, x: jnp.tanh(x @ vs), base.variables,
                      TensorSpec((None, None), "float32"), name="dyn")
    row = rng.normal(size=_ELEMENT).astype(np.float32)
    with HealthMonitor("warmup") as mon:
        reg.deploy("clf", "v1", model=m, batch_size=8)
        got = srv.predict("clf", row)
    assert mon.count(health.WARMUP_COMPLETED) == 0
    np.testing.assert_array_equal(got.output,
                                  _reference(base, row[None])[0])


def test_warmup_failure_surfaces_at_deploy(rng):
    """A model that cannot execute its ladder fails the eager deploy
    loudly (cluster-side this same propagation is what nacks
    srv_prepare and rolls a cutover back)."""
    EngineConfig.serving_warmup = True
    reg, _ = _serving_stack()

    def _explode(vs, x):
        raise RuntimeError("bad weights")

    bad = ModelFunction(_explode, jnp.zeros((1,), jnp.float32),
                        TensorSpec((None,) + _ELEMENT, "float32"),
                        name="bad")
    with pytest.raises(RuntimeError, match="bad weights"):
        reg.deploy("clf", "v1", model=bad, batch_size=8)
