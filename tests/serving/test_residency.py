"""Multi-model HBM residency (ISSUE 13 tentpole): byte-accounted
budget, LRU / weighted eviction, pinning, and bit-identical reload
after eviction with a recorded ``sparkdl.model_load`` cold-start span."""

import threading

import numpy as np
import pytest

import jax.numpy as jnp

from sparkdl_tpu.core import executor, health, telemetry
from sparkdl_tpu.core.health import HealthMonitor
from sparkdl_tpu.core.model_function import ModelFunction, TensorSpec
from sparkdl_tpu.core.telemetry import Telemetry
from sparkdl_tpu.engine.dataframe import EngineConfig
from sparkdl_tpu.serving import (
    ModelRegistry,
    ModelServer,
    ResidencyExhausted,
    ResidencyManager,
)

_ELEMENT = (4,)
_FEATURES = 2
# one fp32 (4, 2) weight matrix = 32 bytes per model
_MODEL_BYTES = _ELEMENT[0] * _FEATURES * 4


@pytest.fixture(autouse=True)
def _fresh_executor():
    saved = EngineConfig.snapshot()
    executor.reset()
    yield
    executor.reset()
    EngineConfig.restore(saved)


def _loader(seed: float, name: str = "resident", calls=None):
    """Zero-arg loader; `calls` (a list) counts cold starts."""

    def load():
        if calls is not None:
            calls.append(seed)
        w = jnp.full((_ELEMENT[0], _FEATURES), np.float32(seed))
        return ModelFunction(lambda vs, x: jnp.tanh(x @ vs), w,
                             TensorSpec((None,) + _ELEMENT, "float32"),
                             name=name)

    return load


def test_weight_bytes_accounts_every_leaf():
    m = _loader(1.0)()
    assert m.weight_bytes() == _MODEL_BYTES
    tree = ModelFunction(lambda vs, x: x @ vs["w"] + vs["b"],
                         {"w": jnp.zeros((4, 2), jnp.float32),
                          "b": jnp.zeros((2,), jnp.float32)},
                         TensorSpec((None, 4), "float32"))
    assert tree.weight_bytes() == 4 * 2 * 4 + 2 * 4


def test_budget_enforced_lru_evicts_coldest():
    """THE acceptance test, part 1: budget holds 2 models; loading a
    3rd evicts the least-recently-used, with the eviction visible in
    health, the counter and status()."""
    res = ResidencyManager(budget_bytes=2 * _MODEL_BYTES)
    calls = []
    for i, name in enumerate(("a", "b", "c")):
        res.register(name, "v1", _loader(float(i + 1), name, calls))
    with Telemetry("residency") as tel:
        with HealthMonitor("residency") as mon:
            res.acquire("a", "v1")
            res.acquire("b", "v1")
            assert res.resident_bytes() == 2 * _MODEL_BYTES
            res.acquire("a", "v1")  # touch a: b is now the LRU
            res.acquire("c", "v1")  # must evict b, not a
        assert res.is_resident("a", "v1")
        assert not res.is_resident("b", "v1")
        assert res.is_resident("c", "v1")
        assert res.resident_bytes() == 2 * _MODEL_BYTES
        evicted = mon.events(health.SERVING_EVICTED)
        assert [(e["model"], e["bytes"]) for e in evicted] == \
            [("b", _MODEL_BYTES)]
        assert tel.metrics.counter(
            telemetry.M_SERVING_EVICTIONS).value == 1
    st = res.status()
    assert st["evictions"] == 1
    assert st["cold_starts"] == 3
    assert st["resident_bytes"] == 2 * _MODEL_BYTES


def test_reload_after_eviction_is_bit_identical_with_cold_start_span():
    """THE acceptance test, part 2: evict, re-acquire — the reload runs
    the loader again under a recorded ``sparkdl.model_load`` span and
    the reloaded model's outputs are bit-identical to pre-eviction."""
    res = ResidencyManager(budget_bytes=_MODEL_BYTES)
    calls = []
    res.register("a", "v1", _loader(0.5, "a", calls))
    res.register("b", "v1", _loader(0.7, "b", calls))
    x = np.linspace(-1.0, 1.0, _ELEMENT[0]).astype(np.float32)[None]
    with Telemetry("reload") as tel:
        with HealthMonitor("reload") as mon:
            before = np.asarray(res.acquire("a", "v1").apply_fn(
                res.acquire("a", "v1").variables, jnp.asarray(x)))
            res.acquire("b", "v1")  # budget of ONE: evicts a
            assert not res.is_resident("a", "v1")
            reloaded = res.acquire("a", "v1")  # cold start #3
            after = np.asarray(reloaded.apply_fn(
                reloaded.variables, jnp.asarray(x)))
        np.testing.assert_array_equal(before, after)
        assert calls == [0.5, 0.7, 0.5]  # the reload re-ran the loader
        spans = tel.tracer.spans(name=telemetry.SPAN_MODEL_LOAD)
        assert len(spans) == 3
        assert {(s["attributes"]["model"], s["attributes"]["version"])
                for s in spans} == {("a", "v1"), ("b", "v1")}
        cold = mon.events(health.SERVING_COLD_START)
        assert len(cold) == 3
        assert all(e["seconds"] >= 0.0 for e in cold)


def test_pinned_models_never_evicted():
    """THE acceptance test, part 3: the pinned (active) version
    survives arbitrary pressure; when the pinned set + the incoming
    load exceed the budget, ResidencyExhausted is raised and NOTHING
    is evicted (failed admits roll back)."""
    res = ResidencyManager(budget_bytes=2 * _MODEL_BYTES)
    res.register("active", "v1", _loader(1.0, "active"), pinned=True)
    res.register("cand", "v1", _loader(2.0, "cand"))
    res.register("big", "v1", _loader(3.0, "big"), pinned=True)
    res.acquire("active", "v1")
    res.acquire("cand", "v1")
    # big is pinned and needs _MODEL_BYTES: cand (unpinned) is evicted,
    # active (pinned) is NOT — even though active is the LRU
    res.acquire("big", "v1")
    assert res.is_resident("active", "v1")
    assert not res.is_resident("cand", "v1")
    # now the pinned set fills the budget entirely: another load cannot
    # be admitted at all
    res.register("over", "v1", _loader(4.0, "over"))
    with pytest.raises(ResidencyExhausted, match="pinned"):
        res.acquire("over", "v1")
    # the failed admit evicted nothing
    assert res.is_resident("active", "v1")
    assert res.is_resident("big", "v1")
    assert res.resident_bytes() == 2 * _MODEL_BYTES


def test_explicit_evict_respects_pin():
    res = ResidencyManager(budget_bytes=4 * _MODEL_BYTES)
    res.register("a", "v1", _loader(1.0), pinned=True)
    res.register("b", "v1", _loader(2.0))
    res.acquire("a", "v1")
    res.acquire("b", "v1")
    assert not res.evict("a", "v1")  # pinned
    assert res.evict("b", "v1")
    assert not res.evict("b", "v1")  # already cold
    res.pin("a", "v1", False)
    assert res.evict("a", "v1")


def test_weighted_policy_evicts_biggest_coldest_first():
    """bytes x idle-age: a large stale model goes before a small one
    of the same age, even when LRU order says otherwise."""
    # budget holds big (4 units) + one small model: admitting the
    # newcomer (1 unit) forces exactly one eviction
    res = ResidencyManager(budget_bytes=5 * _MODEL_BYTES,
                           policy="weighted")

    def big_loader():
        w = jnp.zeros((_ELEMENT[0], _FEATURES * 4), jnp.float32)
        return ModelFunction(lambda vs, x: x @ vs, w,
                             TensorSpec((None,) + _ELEMENT, "float32"),
                             name="big")

    res.register("big", "v1", big_loader)  # 4x the bytes
    res.register("small", "v1", _loader(1.0, "small"))
    res.register("newcomer", "v1", _loader(2.0, "newcomer"))
    res.acquire("big", "v1")      # older
    res.acquire("small", "v1")    # newest of the residents
    # need 1 model's bytes; big's weight (4x bytes, older) dominates
    # even though under LRU big would ALSO be first here — so touch big
    # to make it the MOST recently used; weighted still evicts it
    res.acquire("big", "v1")
    # now LRU would pick small; weighted picks big (4x bytes, age 1)
    res.acquire("newcomer", "v1")
    assert not res.is_resident("big", "v1")
    assert res.is_resident("small", "v1")
    assert res.is_resident("newcomer", "v1")


def test_concurrent_cold_acquires_run_one_loader():
    res = ResidencyManager(budget_bytes=4 * _MODEL_BYTES)
    calls = []
    res.register("a", "v1", _loader(1.0, "a", calls))
    got = [None] * 8
    barrier = threading.Barrier(8)

    def worker(i):
        barrier.wait()
        got[i] = res.acquire("a", "v1")

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
    assert len(calls) == 1  # ONE cold start
    assert all(g is got[0] for g in got)  # everyone got the same object


def test_validation_and_failed_loader_clears_loading():
    with pytest.raises(ValueError, match="budget_bytes"):
        ResidencyManager(budget_bytes=0)
    with pytest.raises(ValueError, match="policy"):
        ResidencyManager(budget_bytes=1, policy="fifo")
    res = ResidencyManager(budget_bytes=4 * _MODEL_BYTES)
    with pytest.raises(KeyError, match="not\\b.*registered"):
        res.acquire("ghost", "v1")
    boom = [True]

    def flaky():
        if boom[0]:
            raise RuntimeError("transient load failure")
        return _loader(1.0)()

    res.register("a", "v1", flaky)
    with pytest.raises(RuntimeError, match="transient"):
        res.acquire("a", "v1")
    boom[0] = False
    assert res.acquire("a", "v1") is not None  # loading flag was cleared


def test_registry_routes_materialization_through_residency(rng):
    """End-to-end: a registry with a residency manager serves through
    ModelServer; evicting the active model makes the NEXT predict a
    recorded cold start with identical output."""
    res = ResidencyManager(budget_bytes=64 * 1024)
    reg = ModelRegistry(residency=res)
    srv = ModelServer(reg)
    w = jnp.full((_ELEMENT[0], _FEATURES), np.float32(0.25))

    def load():
        return ModelFunction(lambda vs, x: jnp.tanh(x @ vs), w,
                             TensorSpec((None,) + _ELEMENT, "float32"),
                             name="served")

    reg.deploy("clf", "v1", loader=load)
    assert res.is_resident("clf", "v1") is False  # lazy until traffic
    row = rng.normal(size=_ELEMENT).astype(np.float32)
    first = srv.predict("clf", row)
    assert res.is_resident("clf", "v1")
    # the registry pinned the active version at deploy time
    assert not res.evict("clf", "v1")
    res.pin("clf", "v1", False)
    assert res.evict("clf", "v1")
    with HealthMonitor("reload") as mon:
        again = srv.predict("clf", row)
    assert mon.count(health.SERVING_COLD_START) == 1
    np.testing.assert_array_equal(first.output, again.output)
