"""Cluster serving plane (ISSUE 17): replicated deployments, worker-
death failover with deadline re-admission, and cluster-atomic hot-swap.

The contract under test is the acceptance list: serving_cluster=False /
cluster_workers=0 keeps the single-process serving path byte-identical
and never imports serving/cluster.py; a kill -9'd replica mid-stream
loses ZERO requests (every one completes within its deadline via
failover or fails classified — no hangs) with exactly one
``serving_failover`` event per moved request and survivor outputs
bit-identical to the single-process run; a draining worker admits no
new predicts but finishes its in-flight ones (zero failover events);
cluster cutover is two-phase atomic (no caller pair ever observes
mixed versions; a failed prepare rolls back with v1 still serving
everywhere); and the merged run report + exporter snapshot carry the
replica map.
"""

import multiprocessing
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from sparkdl_tpu.cluster import router as cluster_router
from sparkdl_tpu.core import executor, health, resilience, telemetry
from sparkdl_tpu.core.health import HealthMonitor
from sparkdl_tpu.core.model_function import ModelFunction, TensorSpec
from sparkdl_tpu.core.resilience import Fault, FaultInjector
from sparkdl_tpu.engine.dataframe import EngineConfig
from sparkdl_tpu.serving import ModelRegistry, ModelServer
from sparkdl_tpu.serving import cluster as serving_cluster

_ELEMENT = (6,)
_FEATURES = 3
_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
# generous per-request deadline: the chaos legs prove zero-hang via
# classified DeadlineExceeded, not via pytest timeouts
_DEADLINE_MS = 60_000.0


@pytest.fixture(autouse=True)
def _cluster_serving_stack():
    saved = EngineConfig.snapshot()
    executor.reset()
    yield
    executor.reset()
    EngineConfig.restore(saved)
    cluster_router.shutdown()  # idempotent; no test leaks a live router
    serving_cluster.reset()


def _arm(workers: int = 2) -> None:
    EngineConfig.cluster_workers = workers
    EngineConfig.serving_cluster = True


def _model(scale: float, name: str = "served") -> ModelFunction:
    rng = np.random.default_rng(7)
    w = jnp.asarray((rng.normal(size=(_ELEMENT[0], _FEATURES)) * scale)
                    .astype(np.float32))
    return ModelFunction(lambda vs, x: jnp.tanh(x @ vs), w,
                         TensorSpec((None,) + _ELEMENT, "float32"),
                         name=name)


def _reference(model: ModelFunction, rows: np.ndarray) -> np.ndarray:
    return np.asarray(jnp.tanh(jnp.asarray(rows) @ model.variables))


def _stack():
    reg = ModelRegistry()
    return reg, ModelServer(reg)


def _router():
    r = cluster_router.maybe_router()
    assert r is not None
    return r


# ---------------------------------------------------------------------------
# The gate: off means OFF
# ---------------------------------------------------------------------------


def test_single_process_serving_never_imports_cluster_serving():
    """cluster_workers=0 (the default) must keep serving/cluster.py
    un-imported, not just unused — pinned in a subprocess because this
    test session itself imports it."""
    script = (
        "import sys\n"
        "import numpy as np\n"
        "import jax.numpy as jnp\n"
        "from sparkdl_tpu.core.model_function import ModelFunction,"
        " TensorSpec\n"
        "from sparkdl_tpu.engine.dataframe import EngineConfig\n"
        "from sparkdl_tpu.serving import ModelRegistry, ModelServer\n"
        "assert EngineConfig.cluster_workers == 0\n"
        "assert EngineConfig.serving_cluster is False\n"
        "w = jnp.ones((6, 3), dtype='float32')\n"
        "m = ModelFunction(lambda vs, x: jnp.tanh(x @ vs), w,"
        " TensorSpec((None, 6), 'float32'), name='m')\n"
        "reg = ModelRegistry(); srv = ModelServer(reg)\n"
        "reg.deploy('clf', 'v1', model=m)\n"
        "out = srv.predict('clf', np.ones(6, dtype='float32'))\n"
        "assert out.version == 'v1'\n"
        "rogue = sorted(m for m in sys.modules if m.startswith("
        "'sparkdl_tpu.cluster') or m == 'sparkdl_tpu.serving.cluster')\n"
        "assert not rogue, rogue\n"
        "print('CLEAN')\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=_REPO + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, timeout=240)
    out = proc.stdout.decode(errors="replace")
    assert proc.returncode == 0, out[-3000:]
    assert "CLEAN" in out


# ---------------------------------------------------------------------------
# Replication, routing, replica map
# ---------------------------------------------------------------------------


def test_cluster_predict_bit_identical_with_replica_map(rng):
    m = _model(1.0)
    # single-process ground truth first (separate stack, no cluster)
    reg0, srv0 = _stack()
    reg0.deploy("clf", "v1", model=m)
    row = rng.normal(size=_ELEMENT).astype(np.float32)
    batch = rng.normal(size=(5,) + _ELEMENT).astype(np.float32)
    ref_row = np.asarray(srv0.predict("clf", row).output)
    ref_batch = np.asarray(srv0.predict("clf", batch).output)

    _arm(2)
    reg, srv = _stack()
    reg.deploy("clf", "v1", model=m)
    got = srv.predict("clf", row, deadline_ms=_DEADLINE_MS)
    assert got.version == "v1"
    np.testing.assert_array_equal(np.asarray(got.output), ref_row)
    got = srv.predict("clf", batch, deadline_ms=_DEADLINE_MS)
    np.testing.assert_array_equal(np.asarray(got.output), ref_batch)

    # satellite: status() carries the per-deployment replica map and
    # the exporter snapshot hook sees the same thing
    status = srv.status()["cluster"]
    assert status["clf"]["active"] == "v1"
    replicas = status["clf"]["replicas"]
    assert len(replicas) == 2
    for view in replicas.values():
        assert view["versions"] == ["v1"]
        assert set(view) == {"versions", "resident", "resident_bytes",
                             "inflight"}
    # locality: exactly one worker served (and is resident); the other
    # stayed cold — routing prefers the hot replica
    resident = [w for w, v in replicas.items() if v["resident"]]
    assert len(resident) == 1
    exported = telemetry.SnapshotExporter._serving_status()
    assert exported is not None and "clf" in exported


def test_merged_report_carries_serving_sections(rng):
    _arm(2)
    reg, srv = _stack()
    reg.deploy("clf", "v1", model=_model(1.0))
    for _ in range(3):
        srv.predict("clf", rng.normal(size=_ELEMENT).astype(np.float32),
                    deadline_ms=_DEADLINE_MS)
    router = _router()
    router.close()
    section = router.cluster_report["serving"]
    # worker-side fold: every replica's stats, predicts summed
    assert section["predicts"] == 3
    assert section["replicas"]["clf"]["v1"]  # model -> version -> workers
    # coordinator-side: the router block
    assert section["router"]["predicts"] == 3
    assert section["router"]["failovers"] == 0
    assert section["router"]["deployments"]["clf"]["active"] == "v1"


# ---------------------------------------------------------------------------
# Chaos: kill -9 one replica mid-stream
# ---------------------------------------------------------------------------


def test_kill_replica_mid_stream_loses_zero_requests(rng):
    """kill -9 one of 2 replicas while K threads stream predicts:
    every request either completes within its deadline via failover or
    fails classified (zero hangs, zero lost); exactly one
    ``serving_failover`` event per moved request; survivor responses
    bit-identical to the single-process run; zero leaked processes."""
    m = _model(1.0)
    reg0, srv0 = _stack()
    reg0.deploy("clf", "v1", model=m)
    rows = rng.normal(size=(18,) + _ELEMENT).astype(np.float32)
    refs = [np.asarray(srv0.predict("clf", r).output) for r in rows]

    _arm(2)
    reg, srv = _stack()
    reg.deploy("clf", "v1", model=m)
    # warm the routed replica so the kill hits a hot path, not a cold
    # load; request 0 doubles as the reference check for the warm path
    warm = srv.predict("clf", rows[0], deadline_ms=_DEADLINE_MS)
    np.testing.assert_array_equal(np.asarray(warm.output), refs[0])

    results = [None] * len(rows)
    errors = [None] * len(rows)
    start = threading.Barrier(4)

    def run(k: int, idxs):
        start.wait()
        for i in idxs:
            try:
                out = srv.predict("clf", rows[i],
                                  deadline_ms=_DEADLINE_MS)
                results[i] = np.asarray(out.output)
            # the chaos contract allows classified failure, never a
            # hang or an unclassified escape
            except Exception as e:  # noqa: BLE001 - classified below
                errors[i] = e

    idxs = list(range(1, len(rows)))
    lanes = [idxs[k::3] for k in range(3)]
    with HealthMonitor("chaos") as mon:
        with FaultInjector.seeded(
                0, serving_worker_kill=Fault(times=1, after=3)):
            threads = [threading.Thread(target=run, args=(k, lanes[k]),
                                        daemon=True)
                       for k in range(3)]
            for t in threads:
                t.start()
            start.wait()
            for t in threads:
                t.join(timeout=180)
            assert not any(t.is_alive() for t in threads), \
                "a predict hung past its deadline"
    # zero lost: every request either answered or failed classified
    for i in idxs:
        if errors[i] is not None:
            assert resilience.classify(errors[i]) in (
                resilience.RETRYABLE, resilience.FATAL)
            continue
        np.testing.assert_array_equal(results[i], refs[i])
    answered = sum(1 for i in idxs if results[i] is not None)
    assert answered >= len(idxs) - 1  # at most the killed dispatch fails
    # exactly-once: N moved requests <-> N serving_failover events,
    # each naming a distinct request id, and the router ledger agrees
    events = mon.events(health.SERVING_FAILOVER)
    assert events, "the injected kill moved no request"
    moved_ids = [e["request"] for e in events]
    assert len(moved_ids) == len(set(moved_ids))
    router = _router()
    router.close()
    section = router.cluster_report["serving"]["router"]
    assert section["failovers"] == len(events)
    assert sorted(section["moved_requests"]) == sorted(moved_ids)
    assert mon.count(health.CLUSTER_WORKER_LOST) == 1
    # zero leaked processes
    cluster_router.shutdown()
    deadline = time.monotonic() + 30
    while multiprocessing.active_children() and \
            time.monotonic() < deadline:
        time.sleep(0.05)
    assert multiprocessing.active_children() == []


def test_failover_exhausted_fails_classified_not_hung(rng):
    """With a single replica, a worker kill cannot fail over — the
    in-flight request must fail RETRYABLE (ServingReplicaLost), fast,
    classified, never hung."""
    _arm(1)
    reg, srv = _stack()
    reg.deploy("clf", "v1", model=_model(1.0))
    row = rng.normal(size=_ELEMENT).astype(np.float32)
    srv.predict("clf", row, deadline_ms=_DEADLINE_MS)  # warm
    with HealthMonitor("solo") as mon:
        with FaultInjector.seeded(0, serving_worker_kill=1):
            with pytest.raises(resilience.ServingReplicaLost):
                srv.predict("clf", row, deadline_ms=_DEADLINE_MS)
    assert resilience.classify(
        resilience.ServingReplicaLost("x")) == resilience.RETRYABLE
    assert mon.count(health.SERVING_FAILOVER) == 0  # nothing MOVED


# ---------------------------------------------------------------------------
# Drain: stop admitting, finish in-flight (satellite 1)
# ---------------------------------------------------------------------------


def test_draining_worker_stops_admitting_but_finishes_inflight(rng):
    _arm(2)
    reg, srv = _stack()
    reg.deploy("clf", "v1", model=_model(1.0))
    row = rng.normal(size=_ELEMENT).astype(np.float32)
    first = srv.predict("clf", row, deadline_ms=_DEADLINE_MS)
    router = _router()
    # SIGTERM the worker that just served (the hot replica): it must
    # drain — finish anything in flight, take no new predicts — while
    # the stream continues uninterrupted on the survivor
    replicas = srv.status()["cluster"]["clf"]["replicas"]
    hot_name = next(w for w, v in replicas.items() if v["resident"])
    with HealthMonitor("drain") as mon:
        hot = next(w for w in router._workers
                   if w.proc.name == hot_name and w.proc.is_alive())
        os.kill(hot.proc.pid, signal.SIGTERM)
        deadline = time.monotonic() + 60
        while hot.wid in router.serving_live_workers() \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        assert hot.wid not in router.serving_live_workers(), \
            "draining worker still admitting"
        for _ in range(6):
            out = srv.predict("clf", row, deadline_ms=_DEADLINE_MS)
            assert out.version == "v1"
        np.testing.assert_array_equal(np.asarray(out.output),
                                      np.asarray(first.output))
        # a drain is not a death: nothing moved, nothing failed over
        assert mon.count(health.SERVING_FAILOVER) == 0
        assert mon.count(health.CLUSTER_WORKER_LOST) == 0
        assert mon.count(health.CLUSTER_WORKER_DRAINING) == 1
        # the preemption drain spawns a replacement, and the spawn
        # top-up re-fans the deployment: the replica map regains its
        # replication factor without any operator action
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            live = router.serving_live_workers()
            status = srv.status()["cluster"]["clf"]["replicas"]
            if len(live) >= 2 and len(status) >= 2:
                break
            time.sleep(0.05)
        assert len(router.serving_live_workers()) >= 2


# ---------------------------------------------------------------------------
# Cluster-atomic hot swap
# ---------------------------------------------------------------------------


def test_cutover_is_cluster_atomic_no_version_mix(rng):
    """K threads stream predicts across a live cutover: for any two
    requests where one STARTED after the other COMPLETED, the later one
    must not observe the older version — the linearizability face of
    'no window where two callers get different versions'."""
    m1, m2 = _model(1.0), _model(2.0)
    _arm(2)
    reg, srv = _stack()
    reg.deploy("clf", "v1", model=m1)
    reg.deploy("clf", "v2", model=m2)  # dark until cut over
    row = rng.normal(size=_ELEMENT).astype(np.float32)
    ref1 = _reference(m1, row[None])[0]
    ref2 = _reference(m2, row[None])[0]
    srv.predict("clf", row, deadline_ms=_DEADLINE_MS)  # warm v1

    log = []  # (t_start, t_end, version)
    log_lock = threading.Lock()
    stop = threading.Event()
    fail = []

    def stream():
        while not stop.is_set():
            t0 = time.monotonic()
            try:
                out = srv.predict("clf", row, deadline_ms=_DEADLINE_MS)
            # sparkdl: allow(broad-retry): not a retry — the worker thread records the failure for the main thread's assertion
            except Exception as e:  # noqa: BLE001 - surfaced below
                fail.append(e)
                return
            t1 = time.monotonic()
            want = ref1 if out.version == "v1" else ref2
            np.testing.assert_array_equal(np.asarray(out.output), want)
            with log_lock:
                log.append((t0, t1, out.version))

    threads = [threading.Thread(target=stream, daemon=True)
               for _ in range(3)]
    for t in threads:
        t.start()
    while len(log) < 6:  # let v1 traffic establish
        time.sleep(0.01)
    with HealthMonitor("swap") as mon:
        prev = srv.cutover("clf", "v2")
    assert prev == "v1"
    assert mon.count(health.SERVING_CUTOVER) == 1
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        with log_lock:
            if any(v == "v2" for _, _, v in log):
                break
        time.sleep(0.01)
    stop.set()
    for t in threads:
        t.join(timeout=120)
    assert not fail, fail
    versions = {v for _, _, v in log}
    assert versions == {"v1", "v2"}  # both sides of the swap observed
    # atomicity: no request started after a v2 completion may be v1
    with log_lock:
        snap = list(log)
    first_v2_end = min(t1 for _, t1, v in snap if v == "v2")
    stragglers = [v for t0, _, v in snap if t0 > first_v2_end]
    assert all(v == "v2" for v in stragglers), snap
    # and the caller-facing registry agrees with the router pointer
    assert reg.active_version("clf") == "v2"


def test_failed_prepare_rolls_back_v1_everywhere(rng):
    """One replica cannot load v2 (its loader raises there): prepare
    must fail, the cutover must roll back — v1 still active AND still
    answering on every replica, serving_prepare_failed recorded, and a
    later predict stream sees only v1."""
    _arm(2)
    reg, srv = _stack()
    m1 = _model(1.0)
    reg.deploy("clf", "v1", model=m1)

    def bad_loader():
        import multiprocessing as mp

        if mp.current_process().name.endswith("-1"):
            raise RuntimeError("v2 weights refuse to load here")
        rng2 = np.random.default_rng(7)
        w = jnp.asarray((rng2.normal(size=(_ELEMENT[0], _FEATURES)) * 2)
                        .astype(np.float32))
        return ModelFunction(lambda vs, x: jnp.tanh(x @ vs), w,
                             TensorSpec((None,) + _ELEMENT, "float32"),
                             name="served")

    reg.deploy("clf", "v2", loader=bad_loader)
    row = rng.normal(size=_ELEMENT).astype(np.float32)
    srv.predict("clf", row, deadline_ms=_DEADLINE_MS)
    with HealthMonitor("prep") as mon:
        with pytest.raises(serving_cluster.CutoverFailed,
                           match="still serving everywhere"):
            srv.cutover("clf", "v2")
        assert mon.count(health.SERVING_PREPARE_FAILED) == 1
        assert mon.count(health.SERVING_CUTOVER) == 0  # nothing flipped
    assert reg.active_version("clf") == "v1"
    for _ in range(4):
        out = srv.predict("clf", row, deadline_ms=_DEADLINE_MS)
        assert out.version == "v1"
    np.testing.assert_array_equal(np.asarray(out.output),
                                  _reference(m1, row[None])[0])
    router = _router()
    router.close()
    section = router.cluster_report["serving"]["router"]
    assert section["prepare_failures"] == 1
    assert section["cutovers"] == 0
    assert section["deployments"]["clf"]["active"] == "v1"


def test_direct_registry_cutover_adopted_cluster_atomically(rng):
    """A bypassing ``registry.cutover`` call converges: the next
    predict notices the pointer mismatch and runs the SAME two-phase
    swap before serving the new version."""
    _arm(2)
    reg, srv = _stack()
    reg.deploy("clf", "v1", model=_model(1.0))
    reg.deploy("clf", "v2", model=_model(2.0))
    row = rng.normal(size=_ELEMENT).astype(np.float32)
    assert srv.predict("clf", row,
                       deadline_ms=_DEADLINE_MS).version == "v1"
    reg.cutover("clf", "v2")  # direct, behind the router's back
    out = srv.predict("clf", row, deadline_ms=_DEADLINE_MS)
    assert out.version == "v2"
    np.testing.assert_array_equal(
        np.asarray(out.output), _reference(_model(2.0), row[None])[0])


def test_rollback_is_cluster_atomic(rng):
    _arm(2)
    reg, srv = _stack()
    reg.deploy("clf", "v1", model=_model(1.0))
    reg.deploy("clf", "v2", model=_model(2.0))
    row = rng.normal(size=_ELEMENT).astype(np.float32)
    srv.predict("clf", row, deadline_ms=_DEADLINE_MS)
    assert srv.cutover("clf", "v2") == "v1"
    assert srv.predict("clf", row,
                       deadline_ms=_DEADLINE_MS).version == "v2"
    assert srv.rollback("clf") == "v2"
    out = srv.predict("clf", row, deadline_ms=_DEADLINE_MS)
    assert out.version == "v1"
    assert reg.active_version("clf") == "v1"


# ---------------------------------------------------------------------------
# AOT bucket-ladder warmup across the cluster (ISSUE 20)
# ---------------------------------------------------------------------------


def test_cluster_prepare_warms_ladder_on_every_replica(rng):
    """srv_prepare materializes through the warmup-wrapped loader: by
    the time a cutover commits, EVERY replica has paid the incoming
    version's full bucket ladder — one warmup_completed per (replica,
    version) cold load, federated into the merged cluster report."""
    EngineConfig.serving_warmup = True  # BEFORE the router spawns:
    # workers inherit EngineConfig at boot
    _arm(2)
    reg, srv = _stack()
    m1 = _model(1.0)
    reg.deploy("clf", "v1", model=m1, batch_size=8)
    row = rng.normal(size=_ELEMENT).astype(np.float32)
    # first predict: router spawns, ONE replica cold-loads (and warms) v1
    out = srv.predict("clf", row, deadline_ms=_DEADLINE_MS)
    assert out.version == "v1"

    def v2_loader():
        rng2 = np.random.default_rng(7)
        w = jnp.asarray((rng2.normal(size=(_ELEMENT[0], _FEATURES)) * 2)
                        .astype(np.float32))
        return ModelFunction(lambda vs, x: jnp.tanh(x @ vs), w,
                             TensorSpec((None,) + _ELEMENT, "float32"),
                             name="served")

    reg.deploy("clf", "v2", loader=v2_loader, batch_size=8)
    srv.cutover("clf", "v2")  # two-phase: prepare warms BOTH replicas
    out = srv.predict("clf", row, deadline_ms=_DEADLINE_MS)
    assert out.version == "v2"
    np.testing.assert_array_equal(np.asarray(out.output),
                                  _reference(v2_loader(), row[None])[0])

    router = _router()
    router.close()
    rep = router.cluster_report
    per_worker = {
        name: snap["health"]["counters"].get(health.WARMUP_COMPLETED, 0)
        for name, snap in rep["workers"].items()}
    assert len(per_worker) == 2
    # v2 prepared (= warmed) on BOTH replicas before the commit; v1
    # warmed only on the replica that served the first request
    assert all(count >= 1 for count in per_worker.values()), per_worker
    assert sum(per_worker.values()) == 3, per_worker
    assert rep["health"]["counters"][health.WARMUP_COMPLETED] == 3
    assert rep["health_consistent"]


def test_cluster_failed_warmup_nacks_prepare_and_rolls_back(rng):
    """The warmup gate has teeth: v2's loader succeeds on every
    replica, but its ladder cannot execute — with serving_warmup armed
    the cold load fails DURING warmup, the prepare nacks, and the
    cutover rolls back with v1 still serving everywhere. Without the
    gate this exact deployment would have prepared fine and detonated
    on the first live request."""
    EngineConfig.serving_warmup = True
    _arm(2)
    reg, srv = _stack()
    m1 = _model(1.0)
    reg.deploy("clf", "v1", model=m1, batch_size=8)

    def dud_loader():
        def _explode(vs, x):
            raise RuntimeError("v2 cannot execute its ladder")

        return ModelFunction(_explode, jnp.zeros((1,), jnp.float32),
                             TensorSpec((None,) + _ELEMENT, "float32"),
                             name="served")

    reg.deploy("clf", "v2", loader=dud_loader, batch_size=8)
    row = rng.normal(size=_ELEMENT).astype(np.float32)
    srv.predict("clf", row, deadline_ms=_DEADLINE_MS)
    with HealthMonitor("warm-prep") as mon:
        with pytest.raises(serving_cluster.CutoverFailed,
                           match="still serving everywhere"):
            srv.cutover("clf", "v2")
        assert mon.count(health.SERVING_PREPARE_FAILED) >= 1
        assert mon.count(health.SERVING_CUTOVER) == 0
    assert reg.active_version("clf") == "v1"
    out = srv.predict("clf", row, deadline_ms=_DEADLINE_MS)
    assert out.version == "v1"
    np.testing.assert_array_equal(np.asarray(out.output),
                                  _reference(m1, row[None])[0])
    router = _router()
    router.close()
    section = router.cluster_report["serving"]["router"]
    assert section["cutovers"] == 0
    assert section["prepare_failures"] >= 1
    assert section["deployments"]["clf"]["active"] == "v1"
