"""Params system tests — Spark ML semantics (SURVEY.md §5.6 parity)."""

import pytest

from sparkdl_tpu.param import (
    HasInputCol, HasOutputCol, Param, Params, TypeConverters, keyword_only,
    SparkDLTypeConverters,
)


class _Widget(HasInputCol, HasOutputCol):
    size = Param("_Widget", "size", "widget size", TypeConverters.toInt)

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, size=None):
        super().__init__()
        self._setDefault(size=3, outputCol="out")
        self._set(**self._input_kwargs)

    @keyword_only
    def setParams(self, inputCol=None, outputCol=None, size=None):
        return self._set(**self._input_kwargs)


def test_defaults_and_set():
    w = _Widget(inputCol="a")
    assert w.getInputCol() == "a"
    assert w.getOutputCol() == "out"  # default
    assert w.getOrDefault("size") == 3
    w.setParams(size=7)
    assert w.getOrDefault(w.size) == 7
    assert w.isSet(w.size) and w.hasDefault(w.size)


def test_type_conversion_and_errors():
    w = _Widget(inputCol="a")
    w.set(w.size, 5.0)  # float that is an int
    assert w.getOrDefault(w.size) == 5
    with pytest.raises(TypeError):
        w.set(w.size, "nope")
    with pytest.raises(TypeError):
        _Widget(inputCol=123)


def test_instances_do_not_share_state():
    w1 = _Widget(inputCol="a")
    w2 = _Widget(inputCol="b")
    w1.setParams(size=9)
    assert w2.getOrDefault("size") == 3
    assert w1.uid != w2.uid
    # Param identity is bound to instance uid
    assert w1.size != w2.size


def test_copy_with_extra_keeps_uid():
    w = _Widget(inputCol="a", size=5)
    extra = {w.size: 11}
    w2 = w.copy(extra)
    assert w2.uid == w.uid
    assert w2.getOrDefault("size") == 11
    assert w.getOrDefault("size") == 5  # original untouched
    w2.setParams(inputCol="z")
    assert w.getInputCol() == "a"


def test_extract_param_map_layering():
    w = _Widget(inputCol="a")
    pm = w.extractParamMap()
    assert pm[w.size] == 3
    pm2 = w.extractParamMap({w.size: 99})
    assert pm2[w.size] == 99


def test_keyword_only_rejects_positional():
    with pytest.raises(TypeError):
        _Widget("a")


def test_explain_params():
    w = _Widget(inputCol="a")
    text = w.explainParams()
    assert "inputCol" in text and "size" in text and "default: 3" in text


def test_supported_name_converter():
    conv = SparkDLTypeConverters.supportedNameConverter(["X", "Y"])
    assert conv("X") == "X"
    with pytest.raises(TypeError):
        conv("Z")


def test_col_map_converters():
    m = SparkDLTypeConverters.asColumnToInputMap({"col": "input"})
    assert m == {"col": "input"}
    with pytest.raises(TypeError):
        SparkDLTypeConverters.asColumnToInputMap([("a", "b")])
    with pytest.raises(TypeError):
        SparkDLTypeConverters.asOutputToColumnMap({"out": ""})


def test_set_image_loader_none_resets():
    from sparkdl_tpu.param import CanLoadImage

    class L(CanLoadImage):
        pass

    loader = L()
    loader.setImageLoader(lambda u: None)
    assert loader.getImageLoader() is not None
    loader.setImageLoader(None)
    assert loader.getImageLoader() is None
