"""Library logging etiquette (ISSUE 4 satellite): a NullHandler on the
``sparkdl_tpu`` root logger, and every module logger routed under that
namespace — apps that configure logging see one coherent tree, apps that
don't see zero output changes (and no "no handlers" warnings)."""

import ast
import logging
import pathlib

import sparkdl_tpu  # noqa: F401 - importing attaches the NullHandler

ROOT = pathlib.Path(__file__).resolve().parent.parent / "sparkdl_tpu"


def test_root_logger_has_null_handler_and_nothing_else():
    root = logging.getLogger("sparkdl_tpu")
    assert any(isinstance(h, logging.NullHandler) for h in root.handlers)
    # the library must not install real handlers (that's the app's job)
    assert all(isinstance(h, logging.NullHandler) for h in root.handlers)
    # and must not fiddle with propagation or levels
    assert root.propagate
    assert root.level == logging.NOTSET


def test_every_module_logger_uses_dunder_name():
    """AST scan: every getLogger call in the library passes __name__ (or
    a dotted sparkdl_tpu.* literal), so all records flow under the
    package namespace the NullHandler and the telemetry run-id stamp
    cover."""
    offenders = []
    for path in sorted(ROOT.rglob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "getLogger"):
                continue
            if not node.args:  # bare getLogger(): the global root
                offenders.append(f"{path.name}:{node.lineno}: root logger")
                continue
            arg = node.args[0]
            ok = (isinstance(arg, ast.Name) and arg.id == "__name__") or (
                isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)
                and arg.value.startswith("sparkdl_tpu"))
            if not ok:
                offenders.append(
                    f"{path.name}:{node.lineno}: "
                    f"{ast.dump(arg)}")
    assert not offenders, (
        "module loggers must be namespaced under sparkdl_tpu "
        f"(getLogger(__name__)): {offenders}")


def test_unconfigured_logging_emits_nothing(capsys):
    """A warning through a library logger with no app handlers configured
    must not print (NullHandler swallows lastResort only when no handler
    exists; here it guarantees no 'no handlers' complaints either)."""
    logger = logging.getLogger("sparkdl_tpu.tests.silent")
    # simulate an unconfigured app: no root handlers during the call
    root_handlers, logging.root.handlers = logging.root.handlers, []
    last_resort, logging.lastResort = logging.lastResort, None
    try:
        logger.warning("should be swallowed")
    finally:
        logging.root.handlers = root_handlers
        logging.lastResort = last_resort
    captured = capsys.readouterr()
    assert "should be swallowed" not in captured.err
    assert "No handlers could be found" not in captured.err
