"""UDF registry + selectExpr serving-path tests (SURVEY.md §3.4 parity)."""

import numpy as np
import pyarrow as pa
import pytest

from sparkdl_tpu.core.model_function import ModelFunction, TensorSpec
from sparkdl_tpu.engine.dataframe import DataFrame
from sparkdl_tpu.image import imageIO
from sparkdl_tpu.udf import (
    registerImageUDF,
    registerTensorUDF,
    registerUDF,
    udf_registry,
)


@pytest.fixture(autouse=True)
def clean_registry():
    before = set(udf_registry.names())
    yield
    for name in set(udf_registry.names()) - before:
        udf_registry.unregister(name)


def test_row_udf_via_select_expr():
    registerUDF("double_it", lambda v: v * 2)
    df = DataFrame.fromColumns({"x": np.array([1.0, 2.0, 3.0])})
    out = df.selectExpr("double_it(x) as y", "x").collect()
    assert [r["y"] for r in out] == [2.0, 4.0, 6.0]
    assert [r["x"] for r in out] == [1.0, 2.0, 3.0]


def test_tensor_model_udf():
    w = np.array([[1.0, 0.0], [0.0, 2.0], [1.0, 1.0]], dtype=np.float32)
    mf = ModelFunction.fromFunction(lambda vs, x: x @ vs["w"], {"w": w},
                                    TensorSpec((None, 3)))
    registerTensorUDF("linmap", mf)
    x = np.arange(12, dtype=np.float32).reshape(4, 3)
    df = DataFrame.fromColumns({"v": x}, numPartitions=2)
    out = df.selectExpr("linmap(v) as o").collect()
    np.testing.assert_allclose(np.array([r["o"] for r in out]), x @ w,
                               rtol=1e-5)


def test_image_model_udf_with_preprocessor(rng):
    arr = rng.integers(0, 255, size=(8, 8, 3), dtype=np.uint8)
    df = DataFrame.fromRows(
        [{"image": imageIO.imageArrayToStruct(arr)}],
        schema=pa.schema([pa.field("image", imageIO.imageSchema)]))
    mf = ModelFunction.fromFunction(lambda vs, x: x.mean(axis=(1, 2)), None,
                                    TensorSpec((None, 8, 8, 3)))
    registerImageUDF("feat", mf, preprocessor=lambda a: a * 0 + 10)
    out = df.selectExpr("feat(image) as f").collect()
    np.testing.assert_allclose(np.array(out[0]["f"]), [10.0, 10.0, 10.0],
                               rtol=1e-5)
    assert list(out[0].keys()) == ["f"]


def test_keras_image_udf(rng, tmp_path):
    keras = pytest.importorskip("keras")
    from keras import layers

    m = keras.Sequential([keras.Input((8, 8, 3)), layers.Flatten(),
                          layers.Dense(2)])
    from sparkdl_tpu.udf import registerKerasImageUDF

    registerKerasImageUDF("kmodel", m)
    arr = rng.integers(0, 255, size=(8, 8, 3), dtype=np.uint8)
    df = DataFrame.fromRows(
        [{"image": imageIO.imageArrayToStruct(arr)}],
        schema=pa.schema([pa.field("image", imageIO.imageSchema)]))
    out = df.selectExpr("kmodel(image) as p").collect()
    want = m.predict(arr[None].astype(np.float32), verbose=0)[0]
    np.testing.assert_allclose(np.array(out[0]["p"]), want, rtol=1e-3,
                               atol=1e-4)


def test_unknown_udf_raises():
    df = DataFrame.fromColumns({"x": np.array([1.0])})
    with pytest.raises(KeyError, match="nope"):
        df.selectExpr("nope(x)")


def test_select_expr_same_source_twice():
    # aliasing must not destroy the source column for later expressions
    df = DataFrame.fromColumns({"a": np.array([1.0, 2.0])})
    out = df.selectExpr("a as x", "a as y", "a").collect()
    assert list(out[0].keys()) == ["x", "y", "a"]
    assert out[0] == {"x": 1.0, "y": 1.0, "a": 1.0}


def test_select_expr_plain_and_alias():
    df = DataFrame.fromColumns({"x": np.array([1.0, 2.0]),
                                "y": np.array([3.0, 4.0])})
    out = df.selectExpr("y as z", "x").collect()
    assert list(out[0].keys()) == ["z", "x"]
    with pytest.raises(ValueError, match="tokenize"):
        df.selectExpr("sum(x) + 1")


def test_register_keras_image_udf_rejects_multi_io():
    keras = pytest.importorskip("keras")
    from keras import layers

    from sparkdl_tpu.udf import registerKerasImageUDF

    a = keras.Input((8, 8, 3), name="a")
    b = keras.Input((8, 8, 3), name="b")
    m = keras.Model([a, b], layers.Add()([a, b]))
    with pytest.raises(ValueError, match="inputMapping"):
        registerKerasImageUDF("multi_io_udf", m)
