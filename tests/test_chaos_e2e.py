"""Chaos suite: composed fault injection across a full files→decode→
transform→fit pipeline (ISSUE 2 acceptance; docs/RESILIENCE.md).

One seeded FaultInjector fires `decode_error` → `engine_task` (worker
loss after compute) → `device_oom` → `transfer_stall` → `preemption` in a
single run; the pipeline must complete, produce results bit-identical to
the fault-free run, and the HealthMonitor report must match the injected
fault counts exactly.
"""

import json
import re
import time

import numpy as np
import pyarrow as pa
import pytest

import jax
import flax.linen as nn

from sparkdl_tpu.core import health, resilience, telemetry
from sparkdl_tpu.core.health import HealthMonitor
from sparkdl_tpu.core.telemetry import Telemetry
from sparkdl_tpu.core.model_function import ModelFunction, TensorSpec
from sparkdl_tpu.core.resilience import Fault, FaultInjector
from sparkdl_tpu.engine import DataFrame, EngineConfig, TaskFailure
from sparkdl_tpu.image import imageIO
from sparkdl_tpu.ml.image_transformer import TPUImageTransformer
from sparkdl_tpu.train import CheckpointManager, TPURunner, Trainer

pytestmark = pytest.mark.chaos

_N_IMAGES = 12
_FEATURES = 4


@pytest.fixture(autouse=True)
def _restore_engine_config():
    # full snapshot of every public knob (ISSUE 6: new overload knobs are
    # covered without listing them — future knobs too)
    saved = EngineConfig.snapshot()
    yield
    EngineConfig.restore(saved)


@pytest.fixture
def image_dir(tmp_path):
    from PIL import Image

    rng = np.random.default_rng(7)
    d = tmp_path / "imgs"
    d.mkdir()
    for i in range(_N_IMAGES):
        arr = rng.integers(0, 255, size=(8, 8, 3), dtype=np.uint8)
        Image.fromarray(arr).save(d / f"img_{i:02d}.png")
    return d


def _feature_model() -> ModelFunction:
    rng = np.random.default_rng(0)
    import jax.numpy as jnp

    w = jnp.asarray(rng.normal(size=(8 * 8 * 3, _FEATURES))
                    .astype(np.float32) * 0.01)
    return ModelFunction(
        lambda vs, x: jnp.tanh(x.reshape((x.shape[0], -1)) @ vs),
        w, TensorSpec((None, 8, 8, 3), "float32"), name="chaos_feat")


class _MLP(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = False):
        return jax.nn.softmax(nn.Dense(2)(nn.relu(nn.Dense(8)(x))), axis=-1)


_MODULE = _MLP()
_VARIABLES = _MODULE.init(jax.random.PRNGKey(0),
                          np.zeros((1, _FEATURES), np.float32))


def _run_pipeline(image_dir, ckpt_dir, feature_model=None):
    """files → decode (1 task) → transform (3 partitions) → fit (TPURunner
    gang, per-step checkpoints). Returns (features, labels, final_state,
    executed-step trace)."""
    # decode stage: one partition task so the composed decode_error +
    # engine_task(finish) faults deterministically hit the same attempt
    df = imageIO.readImages(str(image_dir), numPartition=1)
    df = df.withColumn(
        "label", lambda p: int(re.search(r"img_(\d+)", p).group(1)) % 2,
        ["filePath"], pa.int64())
    df = df.repartition(3)  # materializes the decode; transform fans out
    t = TPUImageTransformer(inputCol="image", outputCol="features",
                            modelFunction=feature_model or _feature_model(),
                            batchSize=8, outputMode="vector")
    rows = t.transform(df).select("features", "label").collect()
    assert all(r["features"] is not None for r in rows)
    x = np.asarray([r["features"] for r in rows], dtype=np.float32)
    y = np.eye(2, dtype=np.float32)[[r["label"] for r in rows]]
    batches = [(x[i:i + 4], y[i:i + 4]) for i in range(0, _N_IMAGES, 4)]
    steps_run = []

    def train_fn(mesh=None):
        trainer, state = Trainer.from_flax(_MODULE, _VARIABLES,
                                           optimizer="sgd",
                                           learning_rate=0.1, mesh=mesh)
        ckpt = CheckpointManager(str(ckpt_dir))
        # prefetch staging explicitly ON (ISSUE 3): the chaos composition
        # must survive background staging with identical health counts and
        # bit-identical outputs (assertions below are unchanged). NOTE:
        # on_step + checkpoint_every=1 force a sync every step here, so
        # this exercises the staging thread, not deferred sync; the
        # genuinely-deferred abort path (preemption between sync points)
        # is covered by tests/train/test_pipeline_fit.py::
        # test_preemption_abort_with_deferred_sync_resumes_exact
        state = trainer.fit(state, batches, epochs=2, checkpoint=ckpt,
                            checkpoint_every=1, on_step=steps_run.append,
                            prefetch=2, sync_every=2)
        ckpt.wait_until_finished()
        ckpt.close()
        return jax.device_get(state)

    final = TPURunner(np=2, max_restarts=2).run(train_fn)
    return x, y, final, steps_run


def test_chaos_pipeline_recovers_bit_identical(image_dir, tmp_path):
    """Acceptance: all five fault points fire in ONE run; the pipeline
    completes; features are bit-identical and trained params match the
    fault-free run; the health report equals the injected counts."""
    x0, y0, final0, steps0 = _run_pipeline(image_dir, tmp_path / "plain")

    inj = FaultInjector.seeded(
        0,
        # row 0's decode degrades to a null struct on the decode task's
        # first attempt...
        decode_error=1,
        # ...and the same attempt's worker dies after computing but before
        # delivering its result — the classified task retry re-decodes
        # everything cleanly (recovery makes decode_error bit-recoverable)
        engine_task=Fault(times=1, when=lambda c: (
            c.get("phase") == "finish" and c["attempt"] == 0)),
        # first full transform chunk OOMs → bucket-halving re-chunk
        device_oom=Fault(times=1, when=lambda c: c["rows"] >= 8),
        # one transient transfer failure → same-chunk retry
        transfer_stall=1,
        # gang preemption after step 3's checkpoint → restart + resume
        preemption=Fault(when=lambda c: c["step"] == 3),
    )
    with inj, HealthMonitor("chaos") as mon:
        x1, y1, final1, steps1 = _run_pipeline(image_dir, tmp_path / "chaos")

    # every armed point actually fired, exactly once
    assert inj.fired == {"decode_error": 1, "engine_task": 1,
                         "device_oom": 1, "transfer_stall": 1,
                         "preemption": 1}

    # bit-identical data-plane results vs the fault-free run
    np.testing.assert_array_equal(x1, x0)
    np.testing.assert_array_equal(y1, y0)
    # checkpoint-resumed training matches: every step executed once, and
    # final params agree with the uninterrupted run
    assert steps1 == steps0 == [1, 2, 3, 4, 5, 6]
    for a, b in zip(jax.tree.leaves(final0.params),
                    jax.tree.leaves(final1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)

    # the health report matches the injected fault counts exactly
    assert mon.count(health.DECODE_DEGRADED) == inj.fired["decode_error"]
    assert mon.count(health.TASK_RETRIED) == inj.fired["engine_task"]
    assert mon.count(health.OOM_RECHUNK) == inj.fired["device_oom"]
    assert mon.count(health.CHUNK_RETRY) == inj.fired["transfer_stall"]
    assert mon.count(health.GANG_RESTART) == inj.fired["preemption"]
    assert mon.count(health.FIT_RESUMED) == 1
    assert mon.count(health.FIT_COMPLETED) == 1
    assert mon.count(health.TASK_QUARANTINED) == 0
    assert mon.count(health.TASK_DEADLINE_EXCEEDED) == 0
    assert mon.count(health.GANG_FATAL) == 0


def test_chaos_run_under_telemetry_scope_produces_run_report(image_dir,
                                                             tmp_path):
    """ISSUE 4 acceptance: the full chaos pipeline under an active
    telemetry scope yields ONE RunReport JSON whose trace holds
    correctly-parented spans from >= 3 distinct threads, whose metric
    snapshot's retry/quarantine counters equal the HealthMonitor counts,
    and whose Chrome-trace export loads as valid JSON — while outputs
    stay bit-identical to the telemetry-off run."""
    x0, y0, final0, steps0 = _run_pipeline(image_dir, tmp_path / "plain")

    inj = FaultInjector.seeded(
        0,
        decode_error=1,
        engine_task=Fault(times=1, when=lambda c: (
            c.get("phase") == "finish" and c["attempt"] == 0)),
        device_oom=Fault(times=1, when=lambda c: c["rows"] >= 8),
        transfer_stall=1,
        preemption=Fault(when=lambda c: c["step"] == 3),
    )
    tel_dir = tmp_path / "tel"
    # monitor OUTSIDE the telemetry scope so the report (written at
    # telemetry exit) folds the still-active monitor in
    with inj, HealthMonitor("chaos-tel") as mon:
        with Telemetry("chaos", out_dir=str(tel_dir)) as tel:
            x1, y1, final1, steps1 = _run_pipeline(image_dir,
                                                   tmp_path / "chaos")
    assert sum(inj.fired.values()) == 5  # every fault actually fired

    # outputs bit-identical to the telemetry-off run
    np.testing.assert_array_equal(x1, x0)
    np.testing.assert_array_equal(y1, y0)
    assert steps1 == steps0
    for a, b in zip(jax.tree.leaves(final0.params),
                    jax.tree.leaves(final1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)

    # ONE run report, written at scope exit, valid JSON
    reports = sorted(tel_dir.glob("sparkdl_run_report_*.json"))
    assert len(reports) == 1
    report = json.load(open(reports[0]))
    assert report["run_id"] == tel.run_id

    # trace: correctly-parented spans from >= 3 distinct threads
    spans = tel.tracer.spans()
    ids = {s["span_id"] for s in spans}
    assert len({s["thread_id"] for s in spans}) >= 3
    for s in spans:
        assert s["trace_id"] == tel.run_id
        if s["name"] != telemetry.SPAN_RUN:
            assert s["parent_id"] in ids, s
    names = {s["name"] for s in spans}
    assert {"sparkdl.run", "sparkdl.materialize", "sparkdl.task",
            "sparkdl.fit", "sparkdl.train_step",
            "sparkdl.stage_batch"} <= names
    # the report's summary agrees with the live tracer
    assert report["trace"]["spans_recorded"] == len(spans)
    assert len(report["trace"]["threads"]) >= 3

    # metric snapshot counters equal the HealthMonitor counts
    counters = report["metrics"]["counters"]
    for event in (health.TASK_RETRIED, health.TASK_QUARANTINED,
                  health.OOM_RECHUNK, health.CHUNK_RETRY,
                  health.GANG_RESTART, health.DECODE_DEGRADED,
                  health.FIT_RESUMED, health.FIT_COMPLETED):
        assert counters.get(telemetry.HEALTH_METRIC_PREFIX + event, 0) \
            == mon.count(event), event
    assert counters["sparkdl.health.task_retried"] == 1
    assert counters.get("sparkdl.health.task_quarantined", 0) == 0
    assert report["health"]["counters"] == mon.report()["counters"]

    # Chrome-trace export loads as valid JSON with per-thread tracks
    trace = json.load(open(report["chrome_trace"]))
    complete = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert len(complete) == len(spans)
    assert len({e["tid"] for e in complete}) >= 3
    assert all(e["ts"] >= 0 and e["dur"] >= 0 for e in complete)


def test_chaos_coalesced_transform_matches_plain_under_faults(image_dir):
    """ISSUE 5 satellite: seeded device_oom + task_stall under
    EngineConfig.coalesce=True yield bit-identical outputs and health
    counts equal to the non-coalesced run (the execution service is
    observationally transparent, faults included)."""
    t = TPUImageTransformer(inputCol="image", outputCol="features",
                            modelFunction=_feature_model(), batchSize=8,
                            outputMode="vector")

    def run(coalesce):
        EngineConfig.coalesce = coalesce
        inj = FaultInjector.seeded(
            0,
            # fires on the first ≥3-valid-row launch, whichever side
            # (coalesced super-batch or per-partition chunk) gets there
            # first — each partition stages 3 valid rows, so it fires in
            # both modes exactly once
            device_oom=Fault(times=1,
                             when=lambda c: c.get("valid", 0) >= 3),
            # partition 2's first task attempt hangs briefly; with no
            # deadline armed the stall surfaces retryable and the task
            # retry heals it
            task_stall=Fault(times=1,
                             when=lambda c: c["partition"] == 2))
        with inj, HealthMonitor() as mon:
            df = imageIO.readImages(str(image_dir), numPartition=4)
            rows = t.transform(df).select("features").collect()
        assert inj.fired == {"device_oom": 1, "task_stall": 1}
        return rows, mon.report()["counters"]

    rows_plain, health_plain = run(coalesce=False)
    rows_coalesced, health_coalesced = run(coalesce=True)
    assert rows_coalesced == rows_plain  # bit-identical, order-preserving
    assert health_coalesced == health_plain
    assert health_plain[health.OOM_RECHUNK] == 1
    assert health_plain[health.TASK_RETRIED] == 1


def test_chaos_pipeline_with_decode_pool_bit_identical(image_dir, tmp_path):
    """ISSUE 9 satellite: the FULL 5-fault chaos run with the
    multi-process decode pool armed (EngineConfig.decode_workers=2) —
    bit-identical outputs and the exact same health counters as the
    pool-off run, with zero worker respawns (no crash fault armed):
    the pool is observationally transparent, faults included."""
    from sparkdl_tpu.core import decode_pool

    x0, y0, final0, steps0 = _run_pipeline(image_dir, tmp_path / "plain")

    EngineConfig.decode_workers = 2
    inj = FaultInjector.seeded(
        0,
        decode_error=1,
        engine_task=Fault(times=1, when=lambda c: (
            c.get("phase") == "finish" and c["attempt"] == 0)),
        device_oom=Fault(times=1, when=lambda c: c["rows"] >= 8),
        transfer_stall=1,
        preemption=Fault(when=lambda c: c["step"] == 3),
    )
    try:
        with inj, HealthMonitor("chaos-pool") as mon:
            x1, y1, final1, steps1 = _run_pipeline(image_dir,
                                                   tmp_path / "chaos")
    finally:
        decode_pool.shutdown()

    assert inj.fired == {"decode_error": 1, "engine_task": 1,
                         "device_oom": 1, "transfer_stall": 1,
                         "preemption": 1}
    np.testing.assert_array_equal(x1, x0)
    np.testing.assert_array_equal(y1, y0)
    assert steps1 == steps0 == [1, 2, 3, 4, 5, 6]
    for a, b in zip(jax.tree.leaves(final0.params),
                    jax.tree.leaves(final1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)
    # the same counter set the pool-off chaos run pins — the decode
    # fault fires in the SUBMITTING process, so pool on/off agree
    assert mon.count(health.DECODE_DEGRADED) == 1
    assert mon.count(health.TASK_RETRIED) == 1
    assert mon.count(health.OOM_RECHUNK) == 1
    assert mon.count(health.CHUNK_RETRY) == 1
    assert mon.count(health.GANG_RESTART) == 1
    assert mon.count(health.FIT_RESUMED) == 1
    assert mon.count(health.FIT_COMPLETED) == 1
    assert mon.count(health.TASK_QUARANTINED) == 0
    assert mon.count(health.DECODE_POOL_RESPAWN) == 0


def test_chaos_pipeline_columnar_fused_bit_identical(image_dir, tmp_path):
    """ISSUE 18 satellite: the FULL 5-fault chaos run with the zero-copy
    columnar plane, device-fused preprocess (a 6x6 model makes the fused
    resize REAL work, not a size-match no-op), AND the decode pool all
    armed — bit-identical to the fault-free run under the same data
    plane, with the exact per-fault health counter set."""
    from sparkdl_tpu.core import decode_pool

    import jax.numpy as jnp

    def small_model() -> ModelFunction:
        rng = np.random.default_rng(0)
        w = jnp.asarray(rng.normal(size=(6 * 6 * 3, _FEATURES))
                        .astype(np.float32) * 0.01)
        return ModelFunction(
            lambda vs, x: jnp.tanh(x.reshape((x.shape[0], -1)) @ vs),
            w, TensorSpec((None, 6, 6, 3), "float32"), name="chaos_feat6")

    EngineConfig.columnar_images = True
    EngineConfig.fused_preprocess = True
    x0, y0, final0, steps0 = _run_pipeline(image_dir, tmp_path / "plain",
                                           feature_model=small_model())

    EngineConfig.decode_workers = 2
    inj = FaultInjector.seeded(
        0,
        decode_error=1,
        engine_task=Fault(times=1, when=lambda c: (
            c.get("phase") == "finish" and c["attempt"] == 0)),
        device_oom=Fault(times=1, when=lambda c: c["rows"] >= 8),
        transfer_stall=1,
        preemption=Fault(when=lambda c: c["step"] == 3),
    )
    try:
        with inj, HealthMonitor("chaos-columnar") as mon:
            x1, y1, final1, steps1 = _run_pipeline(
                image_dir, tmp_path / "chaos", feature_model=small_model())
    finally:
        decode_pool.shutdown()

    assert inj.fired == {"decode_error": 1, "engine_task": 1,
                         "device_oom": 1, "transfer_stall": 1,
                         "preemption": 1}
    np.testing.assert_array_equal(x1, x0)
    np.testing.assert_array_equal(y1, y0)
    assert steps1 == steps0 == [1, 2, 3, 4, 5, 6]
    for a, b in zip(jax.tree.leaves(final0.params),
                    jax.tree.leaves(final1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)
    assert mon.count(health.DECODE_DEGRADED) == 1
    assert mon.count(health.TASK_RETRIED) == 1
    assert mon.count(health.OOM_RECHUNK) == 1
    assert mon.count(health.CHUNK_RETRY) == 1
    assert mon.count(health.GANG_RESTART) == 1
    assert mon.count(health.FIT_RESUMED) == 1
    assert mon.count(health.FIT_COMPLETED) == 1
    assert mon.count(health.TASK_QUARANTINED) == 0
    assert mon.count(health.DECODE_POOL_RESPAWN) == 0


def test_chaos_cluster_worker_kill_bit_identical(image_dir):
    """ISSUE 14 acceptance: the files→decode→featurize leg with the
    cluster plane armed (EngineConfig.cluster_workers=2) and ONE worker
    SIGKILLed mid-stream by the armed `cluster_worker_kill` injection —
    the run completes bit-identical to the in-process run, the death is
    exactly one `cluster_worker_lost` with its held partitions
    re-dispatched, and nothing leaks (no live worker processes, no
    shared-memory segments)."""
    import multiprocessing
    import os

    from sparkdl_tpu.cluster import router as cluster_router

    def featurize():
        df = imageIO.readImages(str(image_dir), numPartition=1)
        df = df.withColumn(
            "label", lambda p: int(re.search(r"img_(\d+)", p).group(1)) % 2,
            ["filePath"], pa.int64())
        df = df.repartition(3)
        t = TPUImageTransformer(inputCol="image", outputCol="features",
                                modelFunction=_feature_model(), batchSize=8,
                                outputMode="vector")
        rows = t.transform(df).select("features", "label").collect()
        x = np.asarray([r["features"] for r in rows], dtype=np.float32)
        y = np.asarray([r["label"] for r in rows], dtype=np.int64)
        return x, y

    def shm_segments():
        if not os.path.isdir("/dev/shm"):
            return set()
        return {n for n in os.listdir("/dev/shm") if n.startswith("psm_")}

    x0, y0 = featurize()  # in-process truth (cluster_workers=0)

    before = shm_segments()
    EngineConfig.cluster_workers = 2
    # dispatch #1 is the decode partition; the kill arms on dispatch #2 —
    # the first transform partition, with the stream mid-flight
    inj = FaultInjector.seeded(0, cluster_worker_kill=Fault(times=1,
                                                            after=1))
    try:
        with inj, HealthMonitor("chaos-cluster") as mon:
            x1, y1 = featurize()
    finally:
        cluster_router.shutdown()

    assert inj.fired == {"cluster_worker_kill": 1}
    np.testing.assert_array_equal(x1, x0)  # bit-identical through the kill
    np.testing.assert_array_equal(y1, y0)
    assert mon.count(health.CLUSTER_WORKER_STARTED) == 2
    assert mon.count(health.CLUSTER_WORKER_LOST) == 1  # ONE death event
    assert mon.count(health.CLUSTER_REDISPATCH) >= 1  # its held partitions
    assert mon.count(health.TASK_FAILED) == 0  # survivors absorbed it all

    # zero leaks: every worker process reaped, no stray cluster children,
    # no shared-memory segments beyond what preceded the run
    router = cluster_router._last_router
    assert all(not w.proc.is_alive() for w in router._workers)
    names = [p.name for p in multiprocessing.active_children()]
    assert not any(n.startswith("sparkdl-cluster") for n in names), names
    assert shm_segments() - before == set()


def test_chaos_pipeline_bf16_tuned_ladder_within_tolerance(image_dir,
                                                           tmp_path):
    """ISSUE 12 acceptance: the FULL 5-fault chaos run with the raw-speed
    inference path armed (bfloat16 featurize + tuned bucket ladder +
    donated buffers — the production defaults the test conftest pins
    off) — every fault fires exactly once, recovery stays DETERMINISTIC
    under low precision (bit-identical to the fault-free bf16 run), and
    the features stay inside the documented bf16 envelope vs the fp32
    fault-free truth (docs/PERF.md "Launch shaping & precision")."""
    from sparkdl_tpu.core import batching

    x_fp32, _, _, _ = _run_pipeline(image_dir, tmp_path / "fp32")

    EngineConfig.inference_precision = "bfloat16"
    EngineConfig.bucket_ladder = "tuned"
    EngineConfig.inference_donate_buffers = True
    batching.reset_planners()
    try:
        x0, y0, final0, steps0 = _run_pipeline(image_dir,
                                               tmp_path / "plain")
        inj = FaultInjector.seeded(
            0,
            decode_error=1,
            engine_task=Fault(times=1, when=lambda c: (
                c.get("phase") == "finish" and c["attempt"] == 0)),
            device_oom=Fault(times=1, when=lambda c: c["rows"] >= 8),
            transfer_stall=1,
            preemption=Fault(when=lambda c: c["step"] == 3),
        )
        with inj, HealthMonitor("chaos-bf16") as mon:
            x1, y1, final1, steps1 = _run_pipeline(image_dir,
                                                   tmp_path / "chaos")
    finally:
        batching.reset_planners()

    assert inj.fired == {"decode_error": 1, "engine_task": 1,
                         "device_oom": 1, "transfer_stall": 1,
                         "preemption": 1}
    # fault recovery is precision-agnostic: the chaos run reproduces the
    # fault-free bf16 run bit-for-bit (padding rows are masked out, so
    # OOM-halved buckets and retuned rungs cannot perturb valid rows)
    np.testing.assert_array_equal(x1, x0)
    np.testing.assert_array_equal(y1, y0)
    assert steps1 == steps0 == [1, 2, 3, 4, 5, 6]
    for a, b in zip(jax.tree.leaves(final0.params),
                    jax.tree.leaves(final1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)
    # tolerance-compared against the fp32 truth: bounded (tanh) head
    np.testing.assert_allclose(x1, x_fp32, atol=0.05)
    # same health counts as the fp32 chaos run — the fast path changes
    # throughput, not the fault story
    assert mon.count(health.DECODE_DEGRADED) == 1
    assert mon.count(health.TASK_RETRIED) == 1
    assert mon.count(health.OOM_RECHUNK) == 1
    assert mon.count(health.CHUNK_RETRY) == 1
    assert mon.count(health.GANG_RESTART) == 1
    assert mon.count(health.FIT_RESUMED) == 1
    assert mon.count(health.FIT_COMPLETED) == 1
    assert mon.count(health.TASK_QUARANTINED) == 0


def test_chaos_fatal_transform_error_retried_zero_times(image_dir):
    """Acceptance: FATAL errors are provably retried zero times, end to
    end — the engine task fails once, and the gang boundary (classify on
    TaskFailure.failure_kind) would not restart it either."""
    df = imageIO.readImages(str(image_dir), numPartition=2)
    calls = []

    def bad(batch):
        calls.append(1)
        raise ValueError("deliberate contract violation")

    with pytest.raises(TaskFailure) as ei:
        df.mapPartitions(bad).collect()
    assert len(calls) == 2  # one attempt per partition, zero retries
    assert ei.value.retries() == 0
    assert resilience.classify(ei.value) == resilience.FATAL


def test_chaos_stalled_partition_fails_via_deadline(image_dir):
    """Acceptance: a deliberately stalled decode partition fails via
    Deadline instead of wedging the materialization."""
    EngineConfig.task_timeout_s = 0.4
    df = imageIO.readImages(str(image_dir), numPartition=3)
    t0 = time.monotonic()
    with FaultInjector.seeded(0, task_stall=Fault(
            when=lambda c: c["partition"] == 2)) as inj:
        with HealthMonitor() as mon:
            with pytest.raises(TaskFailure, match="deadline"):
                df.collect()
    assert inj.fired["task_stall"] == 1
    assert time.monotonic() - t0 < 5.0
    assert mon.count(health.TASK_DEADLINE_EXCEEDED) == 1


def test_chaos_overload_engine_flood_sheds_absorbed_bit_identical(image_dir):
    """ISSUE 6 satellite: the engine flooded with concurrent partitions
    under TINY executor queue caps in shed mode, plus seeded device_oom
    and task_stall — every shed classifies RETRYABLE, the engine's task
    retry absorbs the spike, and the output is bit-identical to the
    fault-free unbounded run. Accounting closes: every EXECUTOR_SHED
    event corresponds 1:1 to a classified task retry whose error was
    ExecutorOverloaded — no silent loss anywhere."""
    from sparkdl_tpu.core import executor as device_executor

    t = TPUImageTransformer(inputCol="image", outputCol="features",
                            modelFunction=_feature_model(), batchSize=8,
                            outputMode="vector")
    df = imageIO.readImages(str(image_dir), numPartition=6)
    baseline = t.transform(df).select("features").collect()

    device_executor.reset()
    EngineConfig.executor_max_queued_requests = 1
    EngineConfig.executor_overload_mode = "shed"
    EngineConfig.coalesce_window_ms = 10.0
    EngineConfig.max_task_retries = 30   # the retry budget absorbs sheds
    EngineConfig.task_retry_delay_s = 0.01
    EngineConfig.max_workers = 6         # all six partitions race
    inj = FaultInjector.seeded(
        0,
        device_oom=Fault(times=1, when=lambda c: c.get("valid", 0) >= 2),
        task_stall=Fault(times=1, when=lambda c: c["partition"] == 2))
    try:
        with inj, HealthMonitor() as mon:
            rows = t.transform(df).select("features").collect()
    finally:
        device_executor.reset()
    assert inj.fired == {"device_oom": 1, "task_stall": 1}

    # no silent loss: bit-identical, order-preserving vs the fault-free run
    assert rows == baseline
    counters = mon.report()["counters"]
    assert counters[health.OOM_RECHUNK] == 1
    assert counters.get(health.TASK_FAILED, 0) == 0
    assert counters.get(health.TASK_QUARANTINED, 0) == 0
    # every shed surfaced as exactly one classified task retry
    shed_retries = [e for e in mon.events(health.TASK_RETRIED)
                    if e.get("error") == "ExecutorOverloaded"]
    assert counters.get(health.EXECUTOR_SHED, 0) == len(shed_retries)
    stall_retries = [e for e in mon.events(health.TASK_RETRIED)
                     if e.get("error") == "TransferStall"]
    assert len(stall_retries) == 1


def test_chaos_overload_accounting_closes_and_breaker_cycles(tmp_path):
    """ISSUE 6 acceptance: one telemetry+health scope over (a) a direct
    executor flood under tiny caps with per-request deadlines and (b) a
    full circuit-breaker trip→fast-fail→probe→recover cycle. The
    accounting closes exactly — submitted == delivered-bit-identical +
    classified-shed + classified-deadline — and the written run report
    shows the whole overload episode: shed/deadline/breaker counters
    equal to the observed outcomes plus live queue-depth and shed-rate
    gauges."""
    import threading

    import jax.numpy as jnp

    from sparkdl_tpu.core import executor as device_executor
    from sparkdl_tpu.core.executor import ExecutorCircuitOpen, \
        ExecutorOverloaded
    from sparkdl_tpu.core.model_function import ModelFunction, TensorSpec
    from sparkdl_tpu.core.resilience import Deadline

    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(6, _FEATURES)).astype(np.float32))
    fail = [False]

    def apply_fn(vs, x):
        def host_hook(a):
            time.sleep(0.05)
            if fail[0]:
                raise ValueError("INVALID_ARGUMENT: poisoned model")
            return a
        x = jax.pure_callback(host_hook,
                              jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return jnp.tanh(x @ vs)

    mf = ModelFunction(apply_fn, w, TensorSpec((None, 6), "float32"),
                       name="overload_chaos")
    device_executor.reset()
    EngineConfig.executor_max_queued_requests = 2
    EngineConfig.executor_overload_mode = "shed"
    # window longer than the per-request deadline: whatever made it into
    # the queue EXPIRES there and must be dropped before a launch — the
    # flood deterministically produces all three outcome classes (one
    # inline delivery, two queued-then-expired, the rest shed)
    EngineConfig.coalesce_window_ms = 100.0
    n = 16
    inputs = [rng.normal(size=(3, 6)).astype(np.float32)
              for _ in range(n)]
    expected = [mf.apply_batch(x, batch_size=32) for x in inputs]
    results = [None] * n
    errors = [None] * n
    barrier = threading.Barrier(n)

    def work(i):
        try:
            barrier.wait()
            results[i] = device_executor.execute(
                mf, inputs[i], batch_size=32, deadline=Deadline(0.03))
        except BaseException as e:  # noqa: BLE001 - partitioned below
            errors[i] = e

    tel_dir = tmp_path / "tel"
    with HealthMonitor("overload") as mon:
        with Telemetry("overload", out_dir=str(tel_dir)) as tel:
            threads = [threading.Thread(target=work, args=(i,))
                       for i in range(n)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30.0)
            assert not any(t.is_alive() for t in threads)

            # -- the breaker cycle, same scope: trip, fast-fail, recover
            EngineConfig.executor_breaker_threshold = 2
            EngineConfig.executor_breaker_cooldown_s = 0.15
            fail[0] = True
            for _ in range(2):
                with pytest.raises(Exception) as ei:
                    device_executor.execute(mf, inputs[0], batch_size=32)
                assert resilience.classify(ei.value) == resilience.FATAL
            with pytest.raises(ExecutorCircuitOpen):
                device_executor.execute(mf, inputs[0], batch_size=32)
            fail[0] = False
            time.sleep(0.2)
            out = device_executor.execute(mf, inputs[0], batch_size=32)
            np.testing.assert_array_equal(out, expected[0])
    device_executor.reset()

    # -- the accounting closes: submitted == delivered + shed + deadline
    delivered = [i for i in range(n) if errors[i] is None]
    shed = [i for i in range(n)
            if isinstance(errors[i], ExecutorOverloaded)]
    deadline_shed = [i for i in range(n)
                     if isinstance(errors[i], resilience.DeadlineExceeded)]
    assert len(delivered) + len(shed) + len(deadline_shed) == n, errors
    # the episode genuinely exercised every outcome class
    assert delivered and shed and deadline_shed, (
        len(delivered), len(shed), len(deadline_shed))
    for i in delivered:
        np.testing.assert_array_equal(results[i], expected[i])
    counters = mon.report()["counters"]
    assert counters.get(health.EXECUTOR_SHED, 0) == len(shed)
    assert counters.get(health.EXECUTOR_DEADLINE_SHED, 0) \
        == len(deadline_shed)
    # the breaker tripped and recovered, visible as health events
    assert counters[health.BREAKER_OPEN] == 1
    assert counters[health.BREAKER_PROBE] == 1
    assert counters[health.BREAKER_CLOSED] == 1

    # -- the run report shows the whole episode
    reports = sorted(tel_dir.glob("sparkdl_run_report_*.json"))
    assert len(reports) == 1
    report = json.load(open(reports[0]))
    assert report["run_id"] == tel.run_id
    rep_counters = report["metrics"]["counters"]
    for event, want in ((health.EXECUTOR_SHED, len(shed)),
                        (health.EXECUTOR_DEADLINE_SHED,
                         len(deadline_shed)),
                        (health.BREAKER_OPEN, 1),
                        (health.BREAKER_PROBE, 1),
                        (health.BREAKER_CLOSED, 1)):
        assert rep_counters.get(
            telemetry.HEALTH_METRIC_PREFIX + event, 0) == want, event
    gauges = report["metrics"]["gauges"]
    assert telemetry.M_EXECUTOR_QUEUE_DEPTH in gauges
    assert telemetry.M_EXECUTOR_SHED_RATE in gauges
    assert report["health"]["counters"] == mon.report()["counters"]


def test_chaos_straggler_hedged_and_deduplicated(image_dir):
    """Acceptance: a straggler decode partition is hedged; the duplicate's
    result is deduplicated deterministically (output equals the
    unhedged run's, each row exactly once)."""
    EngineConfig.speculation = True
    EngineConfig.speculation_quantile = 0.5
    EngineConfig.speculation_min_runtime_s = 0.05
    # fresh, wide pool so the hedge isn't queued behind the straggler
    EngineConfig.max_workers = 9
    df = imageIO.readImages(str(image_dir), numPartition=6)
    baseline = df.collect()
    stalled = set()
    import threading

    lock = threading.Lock()

    def slow_once(batch):
        key = batch.column(0)[0].as_py()
        with lock:
            again = key in stalled
            stalled.add(key)
        if key.endswith("img_10.png") and not again:
            time.sleep(2.0)  # environmental slowness on the primary only
        return batch

    t0 = time.monotonic()
    with HealthMonitor() as mon:
        rows = df.mapPartitions(slow_once).collect()
    assert rows == baseline  # identical, order-preserving, no duplicates
    assert mon.count(health.TASK_HEDGED) == 1
    assert mon.count(health.HEDGE_WON) == 1
    assert time.monotonic() - t0 < 1.5


def test_chaos_overload_slo_timeline_breach_and_recovery(tmp_path):
    """ISSUE 7 satellite: the overload/shed chaos scenario inside a
    Telemetry scope with a short export interval. The periodic snapshot
    timeline must show the shed-rate SLO firing during the flood and
    recovering after — exactly one slo_breach/slo_recovered pair for
    the violated rule — with the windowed view diverging from the
    cumulative one once the flood ages out, and every count consistent
    with the HealthMonitor report."""
    import threading

    import jax.numpy as jnp

    from sparkdl_tpu.core import executor as device_executor
    from sparkdl_tpu.core import slo
    from sparkdl_tpu.core.executor import ExecutorOverloaded

    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(6, _FEATURES)).astype(np.float32))

    def apply_fn(vs, x):
        def host_hook(a):
            time.sleep(0.05)  # a slow model keeps the queue full
            return a
        x = jax.pure_callback(host_hook,
                              jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return jnp.tanh(x @ vs)

    mf = ModelFunction(apply_fn, w, TensorSpec((None, 6), "float32"),
                       name="slo_chaos")
    device_executor.reset()
    EngineConfig.executor_max_queued_requests = 2
    EngineConfig.executor_overload_mode = "shed"
    EngineConfig.coalesce_window_ms = 20.0
    n = 16
    inputs = [rng.normal(size=(3, 6)).astype(np.float32)
              for _ in range(n)]
    errors = [None] * n
    barrier = threading.Barrier(n)

    def work(i):
        try:
            barrier.wait()
            device_executor.execute(mf, inputs[i], batch_size=32)
        except BaseException as e:  # noqa: BLE001 - partitioned below
            errors[i] = e

    # second-scale windows so breach AND recovery land inside one test;
    # the queue-wait threshold is raised so only the shed-rate rule can
    # fire (the acceptance wants one pair per VIOLATED rule)
    rules = slo.default_rules(window_s=0.6, shed_rate_per_s=0.5,
                              queue_wait_p99_s=5.0)
    tel_dir = tmp_path / "tel"
    try:
        with HealthMonitor("slo-chaos") as mon:
            with Telemetry("slo-chaos", out_dir=str(tel_dir),
                           export_interval_s=0.05, window_s=0.6,
                           window_buckets=6, slo_rules=rules) as tel:
                threads = [threading.Thread(target=work, args=(i,))
                           for i in range(n)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=30.0)
                assert not any(t.is_alive() for t in threads)
                # the breach surfaces LIVE, on an exporter tick
                deadline = time.monotonic() + 10.0
                while (mon.count(health.SLO_BREACH) < 1
                       and time.monotonic() < deadline):
                    time.sleep(0.02)
                assert mon.count(health.SLO_BREACH) == 1
                # quiet down: the window slides past the flood
                deadline = time.monotonic() + 10.0
                while (mon.count(health.SLO_RECOVERED) < 1
                       and time.monotonic() < deadline):
                    time.sleep(0.02)
                assert mon.count(health.SLO_RECOVERED) == 1
                # queue waits are recorded at DRAIN time (later than the
                # admission sheds), so their window empties later — wait
                # for it so the final flush proves the windowed view is
                # clean while the cumulative one still holds the episode
                deadline = time.monotonic() + 10.0
                while (tel.metrics.window_snapshot()["histograms"]
                       .get(telemetry.M_QUEUE_WAIT_S,
                            {"count": 0})["count"] > 0
                       and time.monotonic() < deadline):
                    time.sleep(0.02)
    finally:
        device_executor.reset()

    sheds = [e for e in errors if isinstance(e, ExecutorOverloaded)]
    assert sheds  # the flood genuinely shed past the tiny cap
    assert all(e is None or isinstance(e, ExecutorOverloaded)
               for e in errors), errors

    # exactly one breach/recovered pair, and only for the shed rule
    assert mon.count(health.SLO_BREACH) == 1
    assert mon.count(health.SLO_RECOVERED) == 1
    (breach_ev,) = mon.events(health.SLO_BREACH)
    (rec_ev,) = mon.events(health.SLO_RECOVERED)
    assert breach_ev["rule"] == rec_ev["rule"] == "executor_shed_rate"
    assert breach_ev["observed"] >= 0.5
    assert breach_ev["threshold"] == 0.5

    # >= 3 periodic snapshot lines with monotone sequence numbers, and
    # the timeline shows breach -> recovery in order
    lines = [json.loads(line)
             for line in open(tel.exporter.snapshot_path)]
    assert len(lines) >= 3
    assert [line["seq"] for line in lines] == \
        list(range(1, len(lines) + 1))
    breached_at = [i for i, line in enumerate(lines)
                   if line["slo"]["executor_shed_rate"]["breached"]]
    assert breached_at, "no snapshot captured the breach"
    assert any(not line["slo"]["executor_shed_rate"]["breached"]
               for line in lines[breached_at[-1] + 1:] or [lines[-1]]), \
        "no snapshot captured the recovery"

    # the windowed view diverges from the cumulative one after the
    # flood: last-window sheds are zero while the cumulative counter
    # still carries the episode (same for queue-wait p99 — the
    # "current vs forever" split this plane exists for)
    last = lines[-1]
    shed_metric = telemetry.HEALTH_METRIC_PREFIX + health.EXECUTOR_SHED
    assert last["windowed"]["counters"][shed_metric]["count"] == 0
    assert last["cumulative"]["counters"][shed_metric] == len(sheds)
    qw = telemetry.M_QUEUE_WAIT_S
    cum_qw = last["cumulative"]["histograms"].get(qw)
    if cum_qw and cum_qw["count"]:
        assert last["windowed"]["histograms"][qw]["count"] == 0
        assert last["windowed"]["histograms"][qw]["p99"] is None
        assert cum_qw["p99"] is not None
    # during the flood at least one snapshot saw live windowed sheds
    assert any(line["windowed"]["counters"]
               .get(shed_metric, {"count": 0})["count"] > 0
               for line in lines)
    # executor state rode along in every snapshot
    assert all(line["executor"] is not None for line in lines)

    # counts consistent with the HealthMonitor report, and the run
    # report's mirrors agree with the monitor exactly
    counters = mon.report()["counters"]
    assert counters[health.EXECUTOR_SHED] == len(sheds)
    report = json.load(open(tel.report_path))
    for event in (health.EXECUTOR_SHED, health.SLO_BREACH,
                  health.SLO_RECOVERED):
        assert report["metrics"]["counters"].get(
            telemetry.HEALTH_METRIC_PREFIX + event, 0) \
            == counters[event], event
    assert report["timeline"]["snapshots"] == len(lines)
    assert any(e.get("slo_breached") == ["executor_shed_rate"]
               for e in report["timeline"]["entries"])


def _scaled_feature_model(scale: float, name: str) -> ModelFunction:
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(8 * 8 * 3, _FEATURES))
                    .astype(np.float32) * 0.01 * scale)
    return ModelFunction(
        lambda vs, x: jnp.tanh(x.reshape((x.shape[0], -1)) @ vs),
        w, TensorSpec((None, 8, 8, 3), "float32"), name=name)


def _run_serving_pipeline(image_dir, ckpt_dir):
    """ISSUE 13 chaos leg: the SAME files→decode→infer→fit shape as
    _run_pipeline, with the inference stage served ONLINE — a
    sequential stream of row-level ModelServer.predict requests with a
    v1→v2 hot-swap armed at a FIXED request index (and v2 shadowing at
    0.5 before the swap). Sequential requests + the deterministic
    shadow accumulator make the swap point, the shadow set and every
    output reproducible across runs. Returns (outputs, versions,
    final_state, steps_run)."""
    from sparkdl_tpu.serving import ModelRegistry, ModelServer

    # decode stage: one partition task, same fault surface as the
    # engine pipeline (decode_error degrades a row; engine_task kills
    # the attempt after compute; the classified retry re-decodes)
    df = imageIO.readImages(str(image_dir), numPartition=1)
    df = df.withColumn(
        "label", lambda p: int(re.search(r"img_(\d+)", p).group(1)) % 2,
        ["filePath"], pa.int64())
    rows = df.select("image", "label").collect()
    x = np.stack([imageIO.imageStructToArray(r["image"]).astype(np.float32)
                  for r in rows])
    y = np.eye(2, dtype=np.float32)[[r["label"] for r in rows]]

    # serving stage: v1 active, v2 shadowed at 0.5 — 6 requests of 12
    # rows each (>= 8-row launches so device_oom/transfer_stall hit the
    # serving path), hot-swap to v2 before request index 3
    reg = ModelRegistry()
    srv = ModelServer(reg)
    reg.deploy("chaos_served", "v1",
               model=_scaled_feature_model(1.0, "chaos_v1"),
               batch_size=8)
    reg.deploy("chaos_served", "v2",
               model=_scaled_feature_model(2.0, "chaos_v2"),
               batch_size=8)
    reg.shadow("chaos_served", "v2", fraction=0.5)
    outputs, versions = [], []
    for i in range(6):
        if i == 3:
            reg.cutover("chaos_served", "v2")  # mid-stream hot-swap
        got = srv.predict("chaos_served", x)
        outputs.append(np.asarray(got.output))
        versions.append(got.version)

    # fit stage on the v1-served features (identical across runs): the
    # gang preemption + checkpoint resume ride along unchanged
    feats = outputs[0]
    batches = [(feats[i:i + 4], y[i:i + 4])
               for i in range(0, _N_IMAGES, 4)]
    steps_run = []

    def train_fn(mesh=None):
        trainer, state = Trainer.from_flax(_MODULE, _VARIABLES,
                                           optimizer="sgd",
                                           learning_rate=0.1, mesh=mesh)
        ckpt = CheckpointManager(str(ckpt_dir))
        state = trainer.fit(state, batches, epochs=2, checkpoint=ckpt,
                            checkpoint_every=1, on_step=steps_run.append)
        ckpt.wait_until_finished()
        ckpt.close()
        return jax.device_get(state)

    final = TPURunner(np=2, max_restarts=2).run(train_fn)
    return outputs, versions, final, steps_run


def test_chaos_serving_hot_swap_bit_identical(image_dir, tmp_path):
    """ISSUE 13 satellite: the 5-fault chaos composition through
    ModelServer.predict with a mid-stream v1→v2 hot-swap armed — zero
    dropped requests, per-version outputs bit-identical to the
    fault-free swap run, and serving/fit health counts equal to the
    fault-free swap run (the faults add ONLY their recovery events)."""
    from sparkdl_tpu.core import executor as device_executor

    with HealthMonitor("serving-plain") as mon0:
        out0, ver0, final0, steps0 = _run_serving_pipeline(
            image_dir, tmp_path / "plain")
    device_executor.reset()  # a fresh service for the chaos run

    inj = FaultInjector.seeded(
        0,
        decode_error=1,
        engine_task=Fault(times=1, when=lambda c: (
            c.get("phase") == "finish" and c["attempt"] == 0)),
        # the serving launches are 12-row batches chunked at 8: the OOM
        # halves the serving chunk, the stall retries it — both INSIDE
        # a predict call
        device_oom=Fault(times=1, when=lambda c: c["rows"] >= 8),
        transfer_stall=1,
        preemption=Fault(when=lambda c: c["step"] == 3),
    )
    try:
        with inj, HealthMonitor("serving-chaos") as mon:
            out1, ver1, final1, steps1 = _run_serving_pipeline(
                image_dir, tmp_path / "chaos")
    finally:
        device_executor.reset()

    # every armed fault actually fired, exactly once
    assert inj.fired == {"decode_error": 1, "engine_task": 1,
                         "device_oom": 1, "transfer_stall": 1,
                         "preemption": 1}

    # zero dropped / double-served: 6 answers, one per request, with
    # the swap landing at the same fixed index in both runs
    assert len(out1) == len(out0) == 6
    assert ver1 == ver0 == ["v1", "v1", "v1", "v2", "v2", "v2"]
    # per-version outputs bit-identical to the fault-free swap run
    for a, b in zip(out1, out0):
        np.testing.assert_array_equal(a, b)
    # and the two versions genuinely disagree (the swap is observable)
    assert not np.array_equal(out1[0], out1[3])

    # the fit leg resumed to the same result
    assert steps1 == steps0 == [1, 2, 3, 4, 5, 6]
    for a, b in zip(jax.tree.leaves(final0.params),
                    jax.tree.leaves(final1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)

    # serving + fit health counts EQUAL to the fault-free swap run:
    # one cutover, the same deterministic shadow set (requests 1 only:
    # 0.5 accumulates to a fire every 2nd pre-swap request), the same
    # per-version cold starts, one completed fit
    for event in (health.SERVING_CUTOVER, health.SERVING_SHADOW_COMPARED,
                  health.SERVING_COLD_START, health.SERVING_SHED,
                  health.SERVING_SHADOW_ERROR, health.FIT_COMPLETED):
        assert mon.count(event) == mon0.count(event), event
    assert mon.count(health.SERVING_CUTOVER) == 1
    assert mon.count(health.SERVING_SHADOW_COMPARED) == 1
    assert mon.count(health.SERVING_COLD_START) == 2  # v1 + v2, once
    assert mon.count(health.SERVING_SHED) == 0

    # the faults added ONLY their recovery events
    assert mon.count(health.DECODE_DEGRADED) == 1
    assert mon.count(health.TASK_RETRIED) == 1
    assert mon.count(health.OOM_RECHUNK) == 1
    assert mon.count(health.CHUNK_RETRY) == 1
    assert mon.count(health.GANG_RESTART) == 1
    assert mon.count(health.FIT_RESUMED) == 1
    assert mon.count(health.TASK_QUARANTINED) == 0
    assert mon0.count(health.OOM_RECHUNK) == 0
    assert mon0.count(health.GANG_RESTART) == 0


def test_chaos_pipeline_autotune_armed_bit_identical(image_dir, tmp_path):
    """ISSUE 20 satellite: the full 5-fault chaos composition with the
    fused-kernel autotune armed (interpreter-mode shootouts on CPU)
    over a ConvBN-routed feature model. fp32 adoption demands
    bit-exactness against the Flax op order, which the folded-affine
    candidates cannot meet — so every shootout RUNS (the verdict
    ledger proves it) yet nothing is adopted, and the chaos run stays
    bit-identical to the kernels-off fault-free run with health counts
    equal to the injected faults."""
    import jax.numpy as jnp

    from sparkdl_tpu.core import kernels
    from sparkdl_tpu.models.layers import ConvBN

    class _ConvFeat(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = False):
            y = ConvBN(_FEATURES, (1, 1), act=True,
                       kernel_family="chaos")(x, train)
            return jnp.tanh(jnp.mean(y, axis=(1, 2)))

    module = _ConvFeat()
    variables = module.init(jax.random.PRNGKey(1),
                            np.zeros((1, 8, 8, 3), np.float32))

    def conv_model() -> ModelFunction:
        return ModelFunction.fromFlax(
            module, variables, TensorSpec((None, 8, 8, 3), "float32"),
            name="chaos_convbn", train=False)

    EngineConfig.pallas_kernels = "off"
    x0, y0, final0, steps0 = _run_pipeline(image_dir, tmp_path / "plain",
                                           feature_model=conv_model())

    saved_interpret = kernels.INTERPRET
    kernels.INTERPRET = True  # shootouts actually execute on CPU
    kernels.reset()
    EngineConfig.pallas_kernels = "autotune"
    inj = FaultInjector.seeded(
        0,
        decode_error=1,
        engine_task=Fault(times=1, when=lambda c: (
            c.get("phase") == "finish" and c["attempt"] == 0)),
        device_oom=Fault(times=1, when=lambda c: c["rows"] >= 8),
        transfer_stall=1,
        preemption=Fault(when=lambda c: c["step"] == 3),
    )
    try:
        with inj, HealthMonitor("chaos-kernels") as mon:
            x1, y1, final1, steps1 = _run_pipeline(
                image_dir, tmp_path / "chaos",
                feature_model=conv_model())
        verdicts = kernels.verdicts_snapshot()
    finally:
        kernels.INTERPRET = saved_interpret
        kernels.reset()

    assert inj.fired == {"decode_error": 1, "engine_task": 1,
                         "device_oom": 1, "transfer_stall": 1,
                         "preemption": 1}

    # the autotune plane audited the routed sites — and adopted nothing
    assert verdicts, "no kernel site was ever audited"
    assert all(v["adopted"] is False for v in verdicts.values()), verdicts

    # bit-identical to the kernels-off fault-free run
    np.testing.assert_array_equal(x1, x0)
    np.testing.assert_array_equal(y1, y0)
    assert steps1 == steps0 == [1, 2, 3, 4, 5, 6]
    for a, b in zip(jax.tree.leaves(final0.params),
                    jax.tree.leaves(final1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)

    assert mon.count(health.DECODE_DEGRADED) == 1
    assert mon.count(health.TASK_RETRIED) == 1
    assert mon.count(health.OOM_RECHUNK) == 1
    assert mon.count(health.CHUNK_RETRY) == 1
    assert mon.count(health.GANG_RESTART) == 1
    assert mon.count(health.FIT_RESUMED) == 1
    assert mon.count(health.FIT_COMPLETED) == 1
    assert mon.count(health.TASK_QUARANTINED) == 0
    assert mon.count(health.GANG_FATAL) == 0
