"""save(dir)/load(dir) round-trips — VERDICT r2 item 5 (SURVEY.md §5.4).

Criterion: the reloaded stage produces IDENTICAL transform output. The
ModelFunction-backed stages round-trip through jax.export StableHLO (the
frozen-graph path: weights baked in, no Python model class needed at load
time); named transformers round-trip weights through msgpack + the zoo.
"""

import numpy as np
import pyarrow as pa
import pytest

from sparkdl_tpu.engine.dataframe import DataFrame
from sparkdl_tpu.image import imageIO
from sparkdl_tpu.ml import (
    DeepImageFeaturizer,
    DeepImagePredictor,
    KerasImageFileEstimator,
    PipelineModel,
    TPUTransformer,
    load,
)


@pytest.fixture
def image_df(rng):
    rows = [{"image": imageIO.imageArrayToStruct(
        rng.integers(0, 255, size=(32, 32, 3), dtype=np.uint8), origin=str(i))}
        for i in range(6)]
    return DataFrame.fromRows(
        rows, schema=pa.schema([pa.field("image", imageIO.imageSchema)]),
        numPartitions=2)


def _vectors(df, col):
    return np.array([r[col] for r in df.collect()], dtype=np.float32)


def test_featurizer_roundtrip(image_df, tmp_path):
    t = DeepImageFeaturizer(inputCol="image", outputCol="f",
                            modelName="TestNet", batchSize=4)
    want = _vectors(t.transform(image_df), "f")
    t.save(str(tmp_path / "feat"))
    t2 = load(str(tmp_path / "feat"))
    assert isinstance(t2, DeepImageFeaturizer)
    assert t2.getModelName() == "TestNet" and t2.getBatchSize() == 4
    got = _vectors(t2.transform(image_df), "f")
    np.testing.assert_array_equal(got, want)


def test_predictor_roundtrip_with_trained_weights(image_df, tmp_path):
    from sparkdl_tpu.models import registry

    mf = registry.build_predictor("TestNet", weights="random", seed=7)
    t = DeepImagePredictor(inputCol="image", outputCol="p",
                           modelName="TestNet", weights=mf.variables,
                           topK=3)
    want = _vectors(t.transform(image_df), "p")
    t.save(str(tmp_path / "pred"))
    t2 = load(str(tmp_path / "pred"))
    assert t2.getOrDefault(t2.topK) == 3
    got = _vectors(t2.transform(image_df), "p")
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)


def test_fitted_estimator_model_roundtrip(tmp_path):
    keras = pytest.importorskip("keras")
    from keras import layers
    from PIL import Image

    rng = np.random.default_rng(0)
    rows = []
    for i in range(8):
        label = i % 2
        arr = rng.integers(0, 40, size=(8, 8, 3), dtype=np.uint8)
        arr[..., label] += 180
        p = tmp_path / f"img_{i}.png"
        Image.fromarray(arr).save(p)
        rows.append({"uri": str(p), "label": label})
    df = DataFrame.fromRows(rows, numPartitions=2)
    model = keras.Sequential([keras.Input((8, 8, 3)),
                              layers.Rescaling(1 / 255.0), layers.Flatten(),
                              layers.Dense(2, activation="softmax")])
    est = KerasImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label", model=model,
        kerasFitParams={"epochs": 2, "batch_size": 4, "shuffle": False})
    fitted = est.fit(df)
    want = _vectors(fitted.transform(df), "preds")
    fitted.save(str(tmp_path / "fitted"))
    fitted2 = load(str(tmp_path / "fitted"))
    got = _vectors(fitted2.transform(df), "preds")
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_tensor_transformer_roundtrip(rng, tmp_path):
    from sparkdl_tpu.core.model_function import ModelFunction, TensorSpec

    w = rng.normal(size=(6, 3)).astype(np.float32)
    mf = ModelFunction.fromFunction(
        lambda vs, x: x @ vs, w, TensorSpec((None, 6), "float32"))
    t = TPUTransformer(inputCol="x", outputCol="y", modelFunction=mf,
                       batchSize=4)
    x = rng.normal(size=(5, 6)).astype(np.float32)
    df = DataFrame.fromColumns({"x": x})
    want = _vectors(t.transform(df), "y")
    t.save(str(tmp_path / "tt"))
    t2 = load(str(tmp_path / "tt"))
    got = _vectors(t2.transform(df), "y")
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)


def test_pipeline_model_roundtrip(image_df, tmp_path):
    feat = DeepImageFeaturizer(inputCol="image", outputCol="f",
                               modelName="TestNet", batchSize=4)
    pm = PipelineModel([feat])
    want = _vectors(pm.transform(image_df), "f")
    pm.save(str(tmp_path / "pm"))
    pm2 = load(str(tmp_path / "pm"))
    assert isinstance(pm2, PipelineModel) and len(pm2.stages) == 1
    got = _vectors(pm2.transform(image_df), "f")
    np.testing.assert_array_equal(got, want)


def test_keras_transformer_roundtrip(rng, tmp_path):
    keras = pytest.importorskip("keras")
    from keras import layers

    from sparkdl_tpu.ml import KerasTransformer

    m = keras.Sequential([keras.Input((4,)), layers.Dense(5, activation="relu"),
                          layers.Dense(2)])
    t = KerasTransformer(inputCol="x", outputCol="y", model=m, batchSize=4)
    x = rng.normal(size=(6, 4)).astype(np.float32)
    df = DataFrame.fromColumns({"x": x})
    want = _vectors(t.transform(df), "y")
    t.save(str(tmp_path / "kt"))
    t2 = load(str(tmp_path / "kt"))
    got = _vectors(t2.transform(df), "y")
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)


def test_load_refuses_unknown_class(tmp_path):
    import json
    import os

    d = tmp_path / "evil"
    os.makedirs(d)
    with open(d / "metadata.json", "w") as f:
        json.dump({"class": "os.system", "params": {}, "artifacts": {}}, f)
    with pytest.raises(ValueError, match="unknown class"):
        load(str(d))


def test_save_with_custom_image_loader_raises(tmp_path):
    from sparkdl_tpu.core.model_function import ModelFunction, TensorSpec
    from sparkdl_tpu.ml import KerasImageFileModel

    mf = ModelFunction.fromFunction(
        lambda vs, x: x.mean(axis=(1, 2)), None,
        TensorSpec((None, 8, 8, 3), "float32"))
    m = KerasImageFileModel(inputCol="uri", outputCol="o", modelFunction=mf,
                            imageLoader=lambda uri: None)
    with pytest.raises(ValueError, match="imageLoader"):
        m.save(str(tmp_path / "x"))


def test_multi_io_transformer_roundtrip(rng, tmp_path):
    """Dict-input models persist too: export carries one shared symbolic
    batch dim across inputs; the reloaded stage maps the same columns."""
    from sparkdl_tpu.core.model_function import ModelFunction, TensorSpec
    from sparkdl_tpu.ml import TPUTransformer

    def apply_fn(vs, x):
        return {"sum": x["a"] + x["b"]}

    spec = {"a": TensorSpec((None, 4), "float32"),
            "b": TensorSpec((None, 4), "float32")}
    mf = ModelFunction.fromFunction(apply_fn, None, spec, name="two_in")
    t = TPUTransformer(modelFunction=mf,
                       inputMapping={"colA": "a", "colB": "b"},
                       outputMapping={"sum": "s"}, batchSize=4)
    a = rng.normal(size=(5, 4)).astype(np.float32)
    b = rng.normal(size=(5, 4)).astype(np.float32)
    df = DataFrame.fromColumns({"colA": a, "colB": b})
    want = _vectors(t.transform(df), "s")
    t.save(str(tmp_path / "mio"))
    t2 = load(str(tmp_path / "mio"))
    assert isinstance(t2.getModelFunction().input_spec, dict)
    got = _vectors(t2.transform(df), "s")
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)


# -- unfitted estimator / pipeline persistence (VERDICT r3 #6) ---------------


@pytest.fixture
def labeled_uri_df(rng, tmp_path):
    from PIL import Image

    rows = []
    for i in range(16):
        label = i % 2
        arr = rng.integers(0, 40, size=(8, 8, 3), dtype=np.uint8)
        arr[..., label] += 180
        p = tmp_path / f"img_{i}.png"
        Image.fromarray(arr).save(p)
        rows.append({"uri": str(p), "label": label})
    return DataFrame.fromRows(rows, numPartitions=2)


def _tiny_keras_cnn():
    keras = pytest.importorskip("keras")
    from keras import layers

    return keras.Sequential([
        keras.Input((8, 8, 3)),
        layers.Rescaling(1 / 255.0),
        layers.Flatten(),
        layers.Dense(2, activation="softmax")])


def _unfitted_estimator():
    return KerasImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        model=_tiny_keras_cnn(), kerasOptimizer="sgd",
        kerasLoss="categorical_crossentropy",
        kerasFitParams={"epochs": 2, "batch_size": 8,
                        "learning_rate": 0.05, "shuffle": True, "seed": 7})


def test_unfitted_estimator_roundtrip_fit(labeled_uri_df, tmp_path):
    """save -> load -> fit == fitting the original (same seed, same data)."""
    est = _unfitted_estimator()
    est.save(str(tmp_path / "est"))
    est2 = load(str(tmp_path / "est"))
    assert isinstance(est2, KerasImageFileEstimator)
    assert est2.getKerasOptimizer() == "sgd"
    assert est2.getKerasFitParams()["seed"] == 7
    want = _vectors(est.fit(labeled_uri_df).transform(labeled_uri_df), "preds")
    got = _vectors(est2.fit(labeled_uri_df).transform(labeled_uri_df), "preds")
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_unfitted_estimator_modelfile_roundtrip(labeled_uri_df, tmp_path):
    """A modelFile-backed estimator saves self-contained: the artifact is a
    copy, so deleting the original file does not break the reloaded one."""
    import os

    src = str(tmp_path / "src_model.keras")
    _tiny_keras_cnn().save(src)
    est = KerasImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label", modelFile=src,
        kerasLoss="categorical_crossentropy",
        kerasFitParams={"epochs": 1, "batch_size": 8, "seed": 3})
    est.save(str(tmp_path / "est2"))
    os.remove(src)
    est2 = load(str(tmp_path / "est2"))
    model = est2.fit(labeled_uri_df)
    assert len(model.transform(labeled_uri_df).collect()) == 16


def test_unfitted_estimator_save_without_model_raises(tmp_path):
    est = KerasImageFileEstimator(inputCol="uri", outputCol="p",
                                  labelCol="label")
    with pytest.raises(ValueError, match="model or modelFile"):
        est.save(str(tmp_path / "bad"))


def test_unfitted_pipeline_roundtrip_fit(labeled_uri_df, tmp_path):
    """Unfitted Pipeline(stages=[estimator]) round-trips and then fits."""
    from sparkdl_tpu.ml import Pipeline

    pipe = Pipeline(stages=[_unfitted_estimator()])
    pipe.save(str(tmp_path / "pipe"))
    pipe2 = load(str(tmp_path / "pipe"))
    assert isinstance(pipe2, Pipeline)
    assert len(pipe2.getStages()) == 1
    want = _vectors(pipe.fit(labeled_uri_df).transform(labeled_uri_df),
                    "preds")
    got = _vectors(pipe2.fit(labeled_uri_df).transform(labeled_uri_df),
                   "preds")
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
