"""Pipeline/fit/transform/paramMap semantics tests.

Models the reference's reliance on pyspark.ml semantics (SURVEY.md §7 hard
part #4): copy-on-override, fitMultiple laziness/thread-safety, pipeline
stage fitting order.
"""

import threading

import numpy as np
import pytest

from sparkdl_tpu.engine.dataframe import DataFrame
from sparkdl_tpu.ml.base import Estimator, Model, Pipeline, Transformer
from sparkdl_tpu.param.base import Param, keyword_only
from sparkdl_tpu.param.converters import TypeConverters


class AddConst(Transformer):
    value = Param("AddConst", "value", "", typeConverter=TypeConverters.toFloat)

    @keyword_only
    def __init__(self, *, value=1.0, inputCol="x", outputCol="y"):
        super().__init__()
        self._set(**self._input_kwargs)
        self._in = inputCol
        self._out = outputCol

    def _transform(self, dataset):
        v = self.getOrDefault(self.value)
        return dataset.withColumn(self._out, lambda x: x + v,
                                  inputCols=[self._in])


class MeanEstimator(Estimator):
    """Learns the mean of column x; model subtracts it."""

    shift = Param("MeanEstimator", "shift", "", typeConverter=TypeConverters.toFloat)

    @keyword_only
    def __init__(self, *, shift=0.0):
        super().__init__()
        self._setDefault(shift=0.0)
        self._set(**self._input_kwargs)
        self.fit_count = 0

    def _fit(self, dataset):
        self.fit_count += 1
        xs = [r["x"] for r in dataset.collect()]
        mean = float(np.mean(xs)) + self.getOrDefault(self.shift)
        return MeanModel(mean)._set_parent(self)


class MeanModel(Model):
    def __init__(self, mean):
        super().__init__()
        self.mean = mean

    def _transform(self, dataset):
        return dataset.withColumn("centered", lambda x: x - self.mean,
                                  inputCols=["x"])

    def copy(self, extra=None):
        m = MeanModel(self.mean)
        m.parent = self.parent
        return m


@pytest.fixture
def df():
    return DataFrame.fromColumns({"x": np.array([1.0, 2.0, 3.0, 4.0])},
                                 numPartitions=2)


def test_transform_with_params_does_not_mutate(df):
    t = AddConst(value=1.0)
    out = t.transform(df, {t.value: 10.0}).collect()
    assert [r["y"] for r in out] == [11.0, 12.0, 13.0, 14.0]
    # receiver unchanged
    out2 = t.transform(df).collect()
    assert [r["y"] for r in out2] == [2.0, 3.0, 4.0, 5.0]


def test_fit_with_single_param_map(df):
    est = MeanEstimator()
    model = est.fit(df, {est.shift: 1.0})
    assert model.mean == pytest.approx(3.5)
    assert est.getOrDefault(est.shift) == 0.0  # estimator untouched


def test_fit_with_param_map_list_returns_models_in_order(df):
    est = MeanEstimator()
    models = est.fit(df, [{est.shift: 0.0}, {est.shift: 1.0}, {est.shift: 2.0}])
    assert [m.mean for m in models] == pytest.approx([2.5, 3.5, 4.5])


def test_fit_multiple_is_thread_safe(df):
    est = MeanEstimator()
    maps = [{est.shift: float(i)} for i in range(8)]
    it = est.fitMultiple(df, maps)
    results = {}
    lock = threading.Lock()

    def drain():
        while True:
            try:
                i, m = next(it)
            except StopIteration:
                return
            with lock:
                results[i] = m.mean

    threads = [threading.Thread(target=drain) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results == {i: pytest.approx(2.5 + i) for i in range(8)}


def test_pipeline_fits_estimators_on_running_frame(df):
    # AddConst makes x→y, then estimator fits on x (still present)
    pipe = Pipeline(stages=[AddConst(value=1.0), MeanEstimator()])
    pm = pipe.fit(df)
    assert isinstance(pm.stages[1], MeanModel)
    out = pm.transform(df).collect()
    assert [r["centered"] for r in out] == pytest.approx([-1.5, -0.5, 0.5, 1.5])


def test_pipeline_estimator_then_transformer_not_fit_eagerly(df):
    est = MeanEstimator()
    pipe = Pipeline(stages=[est, AddConst(value=1.0)])
    pm = pipe.fit(df)
    # est fit exactly once; AddConst passed through untouched
    assert est.fit_count == 1
    out = pm.transform(df).collect()
    assert [r["y"] for r in out] == [2.0, 3.0, 4.0, 5.0]


def test_pipeline_fit_with_stage_param_override(df):
    # the documented HPO pattern: one param map addressing a stage's param
    est = MeanEstimator()
    pipe = Pipeline(stages=[est])
    pm = pipe.fit(df, {est.shift: 1.0})
    assert pm.stages[0].mean == pytest.approx(3.5)
    # estimator itself untouched
    assert est.getOrDefault(est.shift) == 0.0


def test_copy_ignores_unowned_extra_params(df):
    est = MeanEstimator()
    t = AddConst(value=1.0)
    # t does not own est.shift: must be silently ignored, not raise
    t2 = t.copy({est.shift: 5.0, t.value: 3.0})
    assert t2.getOrDefault(t2.value) == 3.0


def test_pipeline_copy_copies_stages():
    p = Pipeline(stages=[AddConst(value=2.0)])
    q = p.copy()
    assert q.getStages()[0] is not p.getStages()[0]
    assert q.getStages()[0].getOrDefault(q.getStages()[0].value) == 2.0
