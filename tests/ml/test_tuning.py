"""Model selection: ParamGridBuilder / CrossValidator /
TrainValidationSplit + evaluators (the reference's documented HPO
workflow wrapped KerasImageFileEstimator in Spark's CrossValidator).

Oracles: grids are exact cartesian products; randomSplit is
deterministic/disjoint/exhaustive; CV picks the paramMap that actually
generalizes (a deliberately-crippled map must lose); evaluator metrics
match hand-computed values.
"""

import numpy as np
import pytest

from sparkdl_tpu.engine.dataframe import DataFrame
from sparkdl_tpu.ml import (
    CrossValidator,
    LogisticRegression,
    MulticlassClassificationEvaluator,
    ParamGridBuilder,
    RegressionEvaluator,
    TrainValidationSplit,
)


@pytest.fixture
def blobs_df(rng):
    centers = np.array([[4, 0, 0], [0, 4, 0], [0, 0, 4]], np.float32)
    rows = []
    for c in range(3):
        pts = rng.normal(size=(30, 3)).astype(np.float32) * 0.5 + centers[c]
        rows += [{"features": p.tolist(), "label": c} for p in pts]
    order = rng.permutation(len(rows))
    return DataFrame.fromRows([rows[i] for i in order], numPartitions=3)


def test_param_grid_builder():
    lr = LogisticRegression()
    grid = (ParamGridBuilder()
            .addGrid(lr.maxIter, [5, 50])
            .addGrid(lr.regParam, [0.0, 1.0, 10.0])
            .build())
    assert len(grid) == 6
    combos = {(m[lr.maxIter], m[lr.regParam]) for m in grid}
    assert combos == {(a, b) for a in (5, 50) for b in (0.0, 1.0, 10.0)}
    base = (ParamGridBuilder().baseOn({lr.tol: 1e-4})
            .addGrid(lr.maxIter, [5]).build())
    assert base == [{lr.tol: 1e-4, lr.maxIter: 5}]
    with pytest.raises(ValueError):
        ParamGridBuilder().addGrid(lr.maxIter, [])


def test_random_split_properties(rng):
    rows = [{"i": int(i)} for i in range(100)]
    df = DataFrame.fromRows(rows, numPartitions=4)
    a, b, c = df.randomSplit([0.5, 0.3, 0.2], seed=7)
    ids = [set(r["i"] for r in part.collect()) for part in (a, b, c)]
    assert sum(len(s) for s in ids) == 100
    assert ids[0] | ids[1] | ids[2] == set(range(100))
    assert not (ids[0] & ids[1]) and not (ids[1] & ids[2])
    assert 40 <= len(ids[0]) <= 60
    # deterministic in seed
    a2, _, _ = df.randomSplit([0.5, 0.3, 0.2], seed=7)
    assert set(r["i"] for r in a2.collect()) == ids[0]


def test_cross_validator_picks_generalizing_map(blobs_df):
    lr = LogisticRegression(maxIter=100)
    grid = (ParamGridBuilder()
            .addGrid(lr.regParam, [0.0, 1000.0])  # huge L2 cripples map 2
            .build())
    cv = CrossValidator(
        estimator=lr, estimatorParamMaps=grid,
        evaluator=MulticlassClassificationEvaluator(metricName="accuracy"),
        numFolds=3, seed=1)
    model = cv.fit(blobs_df)
    assert len(model.avgMetrics) == 2
    assert model.bestIndex == 0
    assert model.avgMetrics[0] > model.avgMetrics[1]
    assert model.avgMetrics[0] >= 0.95
    out = model.transform(blobs_df).collect()  # delegates to bestModel
    acc = np.mean([r["prediction"] == r["label"] for r in out])
    assert acc >= 0.95


def test_train_validation_split(blobs_df):
    lr = LogisticRegression(maxIter=100)
    grid = ParamGridBuilder().addGrid(lr.regParam, [0.0, 1000.0]).build()
    tvs = TrainValidationSplit(
        estimator=lr, estimatorParamMaps=grid,
        evaluator=MulticlassClassificationEvaluator(metricName="accuracy"),
        trainRatio=0.7, seed=2)
    model = tvs.fit(blobs_df)
    assert len(model.validationMetrics) == 2
    assert model.bestIndex == 0
    with pytest.raises(ValueError, match="trainRatio"):
        TrainValidationSplit(estimator=lr, estimatorParamMaps=grid,
                             evaluator=MulticlassClassificationEvaluator(),
                             trainRatio=1.5).fit(blobs_df)


def test_multiclass_evaluator_metrics():
    rows = [{"prediction": p, "label": l} for p, l in
            [(0, 0), (0, 0), (1, 0), (1, 1), (2, 1), (2, 2)]]
    df = DataFrame.fromRows(rows)
    acc = MulticlassClassificationEvaluator(metricName="accuracy").evaluate(df)
    assert acc == pytest.approx(4 / 6)
    # hand-computed weighted f1 over supports {0:3, 1:2, 2:1}
    # class0: p=2/2? pred==0 twice both correct -> p=1, r=2/3, f1=0.8
    # class1: pred==1 twice, 1 correct -> p=0.5, r=0.5, f1=0.5
    # class2: pred==2 twice, 1 correct -> p=0.5, r=1.0, f1=2/3
    want = (3 * 0.8 + 2 * 0.5 + 1 * (2 / 3)) / 6
    f1 = MulticlassClassificationEvaluator(metricName="f1").evaluate(df)
    assert f1 == pytest.approx(want)
    assert MulticlassClassificationEvaluator().isLargerBetter()


def test_regression_evaluator_metrics():
    rows = [{"prediction": 1.0, "label": 2.0}, {"prediction": 3.0, "label": 3.0},
            {"prediction": 5.0, "label": 4.0}]
    df = DataFrame.fromRows(rows)
    assert RegressionEvaluator(metricName="mse").evaluate(df) == \
        pytest.approx(2 / 3)
    assert RegressionEvaluator(metricName="mae").evaluate(df) == \
        pytest.approx(2 / 3)
    assert not RegressionEvaluator(metricName="rmse").isLargerBetter()
    assert RegressionEvaluator(metricName="r2").isLargerBetter()
    r2 = RegressionEvaluator(metricName="r2").evaluate(df)
    assert r2 == pytest.approx(1.0 - (2 / 3) * 3 / 2.0)


def test_cv_misconfiguration_raises(blobs_df):
    lr = LogisticRegression()
    with pytest.raises(ValueError, match="estimator"):
        CrossValidator(estimatorParamMaps=[{}]).fit(blobs_df)
    with pytest.raises(ValueError, match="ParamGridBuilder"):
        CrossValidator(estimator=lr,
                       evaluator=MulticlassClassificationEvaluator()
                       ).fit(blobs_df)
    with pytest.raises(ValueError, match="numFolds"):
        CrossValidator(estimator=lr, estimatorParamMaps=[{}],
                       evaluator=MulticlassClassificationEvaluator(),
                       numFolds=1).fit(blobs_df)


def test_cross_validator_over_keras_estimator(rng, tmp_path):
    """The reference's documented workflow: CrossValidator wrapping
    KerasImageFileEstimator (upstream README) — per fold, all maps train
    through fitMultiple's shared decode + the ModelFunction step cache."""
    keras = pytest.importorskip("keras")
    from keras import layers
    from PIL import Image

    from sparkdl_tpu.ml import KerasImageFileEstimator

    # keras init is otherwise unseeded: an (occasionally) lucky random
    # init let the deliberately-under-trained map win a fold and flip
    # bestIndex — seed it so the selection outcome is deterministic
    keras.utils.set_random_seed(0)

    rows = []
    for i in range(24):
        label = i % 2
        arr = rng.integers(0, 40, size=(8, 8, 3), dtype=np.uint8)
        arr[..., label] += 180
        p = tmp_path / f"img_{i}.png"
        Image.fromarray(arr).save(p)
        rows.append({"uri": str(p), "label": label})
    df = DataFrame.fromRows(rows, numPartitions=3)
    est = KerasImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        model=keras.Sequential([
            keras.Input((8, 8, 3)), layers.Rescaling(1 / 255.0),
            layers.Flatten(), layers.Dense(2, activation="softmax")]),
        kerasOptimizer="sgd", kerasLoss="sparse_categorical_crossentropy")
    grid = (ParamGridBuilder()
            .addGrid(est.kerasFitParams, [
                {"epochs": 20, "batch_size": 8, "learning_rate": 0.05,
                 "seed": 1},
                {"epochs": 1, "batch_size": 8, "learning_rate": 1e-6,
                 "seed": 1},  # deliberately under-trained
            ]).build())

    class ArgmaxEvaluator(MulticlassClassificationEvaluator):
        def evaluate(self, dataset):
            out = dataset.collect()
            preds = np.array([np.argmax(r["preds"]) for r in out])
            labels = np.array([r["label"] for r in out])
            return float((preds == labels).mean())

    cv = CrossValidator(estimator=est, estimatorParamMaps=grid,
                        evaluator=ArgmaxEvaluator(), numFolds=2, seed=3)
    model = cv.fit(df)
    assert model.bestIndex == 0
    assert model.avgMetrics[0] > model.avgMetrics[1]


# -- BinaryClassificationEvaluator (VERDICT r4 #5) --------------------------

def test_binary_evaluator_hand_computed():
    from sparkdl_tpu.ml import BinaryClassificationEvaluator

    # scores desc: 0.8(+), 0.6(-), 0.4(+), 0.2(-)  P=2 N=2
    # ROC points (fpr,tpr): (0,.5) (.5,.5) (.5,1) (1,1) -> AUC = 0.75
    # PR points (rec,prec): (0,1)^ (.5,1) (.5,.5) (1,2/3) (1,.5)
    #   -> AUPR = 0.5 + avg(0.5, 2/3)*0.5 = 19/24
    rows = [{"rawPrediction": s, "label": l} for s, l in
            [(0.8, 1), (0.6, 0), (0.4, 1), (0.2, 0)]]
    df = DataFrame.fromRows(rows)
    ev = BinaryClassificationEvaluator()
    assert ev.evaluate(df) == pytest.approx(0.75)
    assert ev.isLargerBetter()
    aupr = BinaryClassificationEvaluator(metricName="areaUnderPR").evaluate(df)
    assert aupr == pytest.approx(19 / 24)


def test_binary_evaluator_ties_vectors_and_edges():
    from sparkdl_tpu.ml import BinaryClassificationEvaluator

    # all-tied scores collapse to one threshold -> chance AUC 0.5
    tied = DataFrame.fromRows(
        [{"rawPrediction": 0.5, "label": l} for l in (1, 0, 1, 0)])
    assert BinaryClassificationEvaluator().evaluate(tied) == \
        pytest.approx(0.5)
    # probability-vector column: last element is the positive class
    vec = DataFrame.fromRows(
        [{"probability": [1 - s, s], "label": l} for s, l in
         [(0.9, 1), (0.8, 1), (0.2, 0), (0.1, 0)]])
    ev = BinaryClassificationEvaluator(rawPredictionCol="probability")
    assert ev.evaluate(vec) == pytest.approx(1.0)
    assert BinaryClassificationEvaluator(
        rawPredictionCol="probability",
        metricName="areaUnderPR").evaluate(vec) == pytest.approx(1.0)
    # single-class input is undefined
    with pytest.raises(ValueError, match="both classes"):
        BinaryClassificationEvaluator().evaluate(DataFrame.fromRows(
            [{"rawPrediction": 0.5, "label": 1}]))
    # non-binary labels rejected
    with pytest.raises(ValueError, match="binary"):
        BinaryClassificationEvaluator().evaluate(DataFrame.fromRows(
            [{"rawPrediction": 0.5, "label": 2}]))


def test_binary_evaluator_in_cross_validator(rng):
    """CV integration: AUC-driven selection over a binary problem."""
    from sparkdl_tpu.ml import BinaryClassificationEvaluator

    x = rng.normal(size=(80, 4)).astype(np.float32)
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(int)
    df = DataFrame.fromRows(
        [{"features": x[i].tolist(), "label": int(y[i])} for i in range(80)],
        numPartitions=2)
    lr = LogisticRegression(maxIter=100)
    grid = ParamGridBuilder().addGrid(lr.regParam, [0.0, 1000.0]).build()
    cv = CrossValidator(
        estimator=lr, estimatorParamMaps=grid,
        evaluator=BinaryClassificationEvaluator(
            rawPredictionCol="probability"),
        numFolds=2, seed=5)
    model = cv.fit(df)
    assert model.bestIndex == 0
    assert model.avgMetrics[0] > 0.9


# -- parallelism (VERDICT r4 #4) --------------------------------------------

def test_parallelism_matches_serial(blobs_df):
    lr = LogisticRegression(maxIter=100)
    grid = (ParamGridBuilder()
            .addGrid(lr.regParam, [0.0, 1.0, 1000.0]).build())
    ev = MulticlassClassificationEvaluator(metricName="accuracy")
    serial = CrossValidator(estimator=lr, estimatorParamMaps=grid,
                            evaluator=ev, numFolds=2, seed=4,
                            parallelism=1).fit(blobs_df)
    par = CrossValidator(estimator=lr, estimatorParamMaps=grid,
                         evaluator=ev, numFolds=2, seed=4,
                         parallelism=2).fit(blobs_df)
    assert par.bestIndex == serial.bestIndex
    np.testing.assert_allclose(par.avgMetrics, serial.avgMetrics,
                               rtol=1e-6)


def test_parallelism_overlaps_fits(blobs_df):
    """parallelism=2 must actually drain fitMultiple concurrently: with a
    per-fit stall (the host-side work a real fit overlaps with device
    steps), the two fits' [enter, exit] windows must overlap in time —
    a deterministic concurrency check, not a wall-clock race."""
    import time

    from sparkdl_tpu.ml.base import Model as BaseModel

    windows = []

    class _SleepModel(BaseModel):
        def _transform(self, dataset):
            return dataset.withColumn(
                "prediction", lambda lab: float(lab), inputCols=["label"])

    class _SleepEstimator(LogisticRegression):
        def _fit(self, dataset):
            enter = time.monotonic()
            time.sleep(0.3)
            windows.append((enter, time.monotonic()))
            return _SleepModel()

    grid = [{}, {}]  # two identical maps; only concurrency matters
    ev = MulticlassClassificationEvaluator(metricName="accuracy")

    def overlapped(parallelism):
        windows.clear()
        TrainValidationSplit(
            estimator=_SleepEstimator(), estimatorParamMaps=grid,
            evaluator=ev, trainRatio=0.7, seed=0,
            parallelism=parallelism).fit(blobs_df)
        # 3 fits total: the two grid maps + the final best-map refit;
        # only the first two (the grid fits) can overlap
        assert len(windows) == 3
        (a0, a1), (b0, b1) = sorted(windows)[:2]
        return b0 < a1  # second fit entered before the first exited

    assert not overlapped(1)
    assert overlapped(2)


# -- tuning persistence (VERDICT r4 #3) -------------------------------------

def test_cross_validator_roundtrip_and_refit(tmp_path, blobs_df):
    from sparkdl_tpu.ml import load

    lr = LogisticRegression(maxIter=100)
    grid = ParamGridBuilder().addGrid(lr.regParam, [0.0, 1000.0]).build()
    cv = CrossValidator(
        estimator=lr, estimatorParamMaps=grid,
        evaluator=MulticlassClassificationEvaluator(metricName="accuracy"),
        numFolds=3, seed=1, parallelism=2)
    cv.save(str(tmp_path / "cv"))
    loaded = load(str(tmp_path / "cv"))
    assert isinstance(loaded, CrossValidator)
    assert loaded.getNumFolds() == 3
    assert loaded.getSeed() == 1
    assert loaded.getParallelism() == 2
    assert isinstance(loaded.estimator, LogisticRegression)
    assert loaded.estimator.getMaxIter() == 100
    assert loaded.evaluator.getMetricName() == "accuracy"
    assert [{p.name: v for p, v in m.items()}
            for m in loaded.estimatorParamMaps] == [
        {"regParam": 0.0}, {"regParam": 1000.0}]
    # load-then-refit selects the same map as the original would
    model = loaded.fit(blobs_df)
    assert model.bestIndex == 0


def test_cross_validator_model_roundtrip(tmp_path, blobs_df):
    from sparkdl_tpu.ml import CrossValidatorModel, load

    lr = LogisticRegression(maxIter=100)
    grid = ParamGridBuilder().addGrid(lr.regParam, [0.0, 1000.0]).build()
    cv = CrossValidator(
        estimator=lr, estimatorParamMaps=grid,
        evaluator=MulticlassClassificationEvaluator(metricName="accuracy"),
        numFolds=2, seed=1)
    model = cv.fit(blobs_df)
    model.save(str(tmp_path / "cvm"))
    loaded = load(str(tmp_path / "cvm"))
    assert isinstance(loaded, CrossValidatorModel)
    assert loaded.bestIndex == model.bestIndex
    np.testing.assert_allclose(loaded.avgMetrics, model.avgMetrics)
    # load-then-transform equals the original model's transform
    want = model.transform(blobs_df).collect()
    got = loaded.transform(blobs_df).collect()
    np.testing.assert_allclose(
        [r["prediction"] for r in got], [r["prediction"] for r in want])


def test_train_validation_split_roundtrip(tmp_path, blobs_df):
    from sparkdl_tpu.ml import TrainValidationSplitModel, load

    lr = LogisticRegression(maxIter=100)
    grid = ParamGridBuilder().addGrid(lr.regParam, [0.0, 1000.0]).build()
    tvs = TrainValidationSplit(
        estimator=lr, estimatorParamMaps=grid,
        evaluator=MulticlassClassificationEvaluator(metricName="accuracy"),
        trainRatio=0.7, seed=2)
    tvs.save(str(tmp_path / "tvs"))
    loaded = load(str(tmp_path / "tvs"))
    assert isinstance(loaded, TrainValidationSplit)
    assert loaded.getTrainRatio() == pytest.approx(0.7)
    model = loaded.fit(blobs_df)
    assert model.bestIndex == 0
    model.save(str(tmp_path / "tvsm"))
    reloaded = load(str(tmp_path / "tvsm"))
    assert isinstance(reloaded, TrainValidationSplitModel)
    np.testing.assert_allclose(reloaded.validationMetrics,
                               model.validationMetrics)


def test_tuning_persistence_rejects_unserializable_grid(tmp_path, blobs_df):
    """Nested-stage param maps (params the estimator doesn't own) fail at
    save with a clear message, not silently on load."""
    lr = LogisticRegression()
    other = MulticlassClassificationEvaluator()
    bad_grid = [{other.metricName: "accuracy"}]
    cv = CrossValidator(estimator=lr, estimatorParamMaps=bad_grid,
                        evaluator=other, numFolds=2)
    with pytest.raises(ValueError, match="does not own"):
        cv.save(str(tmp_path / "bad"))


def test_regression_evaluator_large_mean_r2():
    """r2 must survive labels with a huge mean (streaming Welford merge,
    not the cancelling raw-moment form)."""
    base = 1e8
    rows = [{"prediction": base + v + 0.1, "label": base + v}
            for v in (0.0, 1.0, 2.0)]
    df = DataFrame.fromRows(rows, numPartitions=3)
    r2 = RegressionEvaluator(metricName="r2").evaluate(df)
    # SStot = 2.0, SSres = 3 * 0.01 -> r2 = 1 - 0.03/2
    assert r2 == pytest.approx(1.0 - 0.03 / 2.0, rel=1e-6)


def test_grid_param_name_collision_rejected_at_save(tmp_path):
    """A foreign param whose NAME collides with one the estimator owns
    must be rejected by identity at save — resolving it by name on load
    would silently rebind the grid to the estimator's param (ADVICE r5)."""
    from sparkdl_tpu.param.base import Param, Params

    class Foreign(Params):
        maxIter = Param("Foreign", "maxIter", "colliding name")

    lr = LogisticRegression()
    bad_grid = [{Foreign().maxIter: 5}]
    cv = CrossValidator(estimator=lr, estimatorParamMaps=bad_grid,
                        evaluator=MulticlassClassificationEvaluator(),
                        numFolds=2)
    with pytest.raises(ValueError, match="collides"):
        cv.save(str(tmp_path / "collide"))
    # the estimator's own param still persists fine
    ok = CrossValidator(
        estimator=lr,
        estimatorParamMaps=[{lr.maxIter: 5}],
        evaluator=MulticlassClassificationEvaluator(), numFolds=2)
    ok.save(str(tmp_path / "ok"))


def test_binary_evaluator_aupr_anchors_at_first_precision():
    """Spark parity: the PR curve starts at (0, firstPrecision), not an
    optimistic (0, 1.0) — visible when the top threshold group holds a
    tie between a positive and a negative (ADVICE r5)."""
    from sparkdl_tpu.ml import BinaryClassificationEvaluator

    # scores desc: {0.5: (+,-)} {0.2: +} {0.1: -}   P=2 N=2
    # curve (rec, prec): (.5, .5) (1, 2/3) (1, .5); anchor (0, .5)
    # trapezoid: 0→.5: .5*.5=.25 ; .5→1: avg(.5,2/3)*.5=7/24 -> 13/24
    # (the old (0,1.0) anchor would give .375 + 7/24 = 2/3)
    rows = [{"rawPrediction": s, "label": l} for s, l in
            [(0.5, 1), (0.5, 0), (0.2, 1), (0.1, 0)]]
    df = DataFrame.fromRows(rows)
    aupr = BinaryClassificationEvaluator(
        metricName="areaUnderPR").evaluate(df)
    assert aupr == pytest.approx(13 / 24)
