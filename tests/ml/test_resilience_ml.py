"""ML-layer degradation: corrupt image rows → null output cells, the
partition completes, drops surface as a warning (docs/RESILIENCE.md)."""

import logging

import numpy as np

import jax.numpy as jnp

from sparkdl_tpu.core import ModelFunction, TensorSpec
from sparkdl_tpu.core.resilience import FaultInjector
from sparkdl_tpu.engine.dataframe import DataFrame
from sparkdl_tpu.image import imageIO
from sparkdl_tpu.ml.image_transformer import TPUImageTransformer


def _mean_model():
    return ModelFunction.fromFunction(
        lambda vs, x: jnp.mean(x, axis=(1, 2)), None,
        TensorSpec((None, 8, 8, 3)))


def _image_df(rng, n=6, corrupt=()):
    structs = [imageIO.imageArrayToStruct(
        rng.integers(0, 255, (8, 8, 3), dtype=np.uint8), origin=f"r{i}")
        for i in range(n)]
    for i, how in corrupt:
        if how == "truncate":
            structs[i] = dict(structs[i], data=structs[i]["data"][:10])
        elif how == "badmode":
            structs[i] = dict(structs[i], mode=99)
    return structs, DataFrame.fromRows([{"image": s} for s in structs])


def test_corrupt_rows_yield_null_cells_partition_completes(rng, caplog):
    structs, df = _image_df(rng, corrupt=[(2, "truncate"), (4, "badmode")])
    t = TPUImageTransformer(inputCol="image", outputCol="out",
                            modelFunction=_mean_model(), batchSize=4)
    with caplog.at_level(logging.WARNING,
                         logger="sparkdl_tpu.ml.image_transformer"):
        rows = t.transform(df).collect()
    outs = [r["out"] for r in rows]
    assert [i for i, o in enumerate(outs) if o is None] == [2, 4]
    # surviving rows compute exactly what an all-clean run would
    for i in (0, 1, 3, 5):
        want = imageIO.imageStructToArray(structs[i]).astype(
            np.float32).mean(axis=(0, 1))
        np.testing.assert_allclose(np.asarray(outs[i], dtype=np.float32),
                                   want, rtol=1e-5)
    # the per-partition drop count is surfaced
    assert any("undecodable image row" in r.message
               for r in caplog.records)


def test_injected_decode_error_yields_null_cell(rng):
    # non-uniform sizes force the per-row (decode) path where the
    # decode_error injection point lives
    structs = [imageIO.imageArrayToStruct(
        rng.integers(0, 255, (8 + (i == 0), 8, 3), dtype=np.uint8))
        for i in range(4)]
    df = DataFrame.fromRows([{"image": s} for s in structs])
    t = TPUImageTransformer(inputCol="image", outputCol="out",
                            modelFunction=_mean_model(), batchSize=4,
                            inputSize=(8, 8))
    baseline = [r["out"] for r in t.transform(df).collect()]
    assert all(o is not None for o in baseline)
    with FaultInjector.seeded(0, decode_error=1) as inj:
        outs = [r["out"] for r in t.transform(df).collect()]
    assert inj.fired["decode_error"] == 1
    assert sum(o is None for o in outs) == 1
    # uncorrupted rows unchanged
    for b, o in zip(baseline, outs):
        if o is not None:
            np.testing.assert_array_equal(np.asarray(b), np.asarray(o))


def test_predictor_corrupt_row_decodes_to_null_topk(rng):
    """End to end through DeepImagePredictor: a corrupt image row flows
    through as a null raw vector and a null decoded top-K cell; the
    remaining rows still decode (docs/RESILIENCE.md)."""
    from sparkdl_tpu.ml.named_image import DeepImagePredictor

    structs = [imageIO.imageArrayToStruct(
        rng.integers(0, 255, (32, 32, 3), dtype=np.uint8))
        for _ in range(4)]
    structs[1] = dict(structs[1], data=structs[1]["data"][:13])  # corrupt
    df = DataFrame.fromRows([{"image": s} for s in structs])
    p = DeepImagePredictor(inputCol="image", outputCol="preds",
                           modelName="TestNet", decodePredictions=True,
                           topK=3, batchSize=4)
    rows = p.transform(df).collect()
    assert len(rows) == 4
    assert rows[1]["preds"] is None
    for i in (0, 2, 3):
        entry = rows[i]["preds"]
        assert len(entry) == 3
        assert all(e["class"] for e in entry)
