"""DeepImageFeaturizer / DeepImagePredictor tests.

Uses TestNet (tiny deterministic model, SURVEY.md §2.2 Models.scala parity)
so tests don't need pretrained weights, exactly like the reference's Scala
suite did.
"""

import numpy as np
import pyarrow as pa
import pytest

from sparkdl_tpu.engine.dataframe import DataFrame
from sparkdl_tpu.image import imageIO
from sparkdl_tpu.ml import DeepImageFeaturizer, DeepImagePredictor
from sparkdl_tpu.models import registry


@pytest.fixture
def image_df(rng):
    rows = []
    for i in range(5):
        arr = rng.integers(0, 255, size=(40, 36, 3), dtype=np.uint8)
        rows.append({"image": imageIO.imageArrayToStruct(arr, origin=f"i{i}")})
    return DataFrame.fromRows(
        rows, schema=pa.schema([pa.field("image", imageIO.imageSchema)]),
        numPartitions=2)


def test_featurizer_output_dim_and_determinism(image_df):
    f = DeepImageFeaturizer(inputCol="image", outputCol="features",
                            modelName="TestNet", batchSize=4)
    out1 = f.transform(image_df).collect()
    out2 = f.transform(image_df).collect()
    spec = registry.get_model_spec("TestNet")
    assert len(out1[0]["features"]) == spec.feature_dim
    np.testing.assert_array_equal(
        np.array([r["features"] for r in out1]),
        np.array([r["features"] for r in out2]))


def test_featurizer_matches_direct_model_function(image_df):
    # oracle: the same registry ModelFunction applied by hand, with the
    # SAME resize policy the transformer's uniform fast path uses (host
    # native downscale / device bilinear — both no-antialias pixel-center,
    # NOT the PIL path; see ml/image_transformer._resize_uniform_batch).
    from sparkdl_tpu.ml.image_transformer import _resize_uniform_batch

    f = DeepImageFeaturizer(inputCol="image", outputCol="features",
                            modelName="TestNet")
    got = np.array([r["features"]
                    for r in f.transform(image_df).collect()], dtype=np.float32)
    mf = registry.build_featurizer("TestNet")
    spec = registry.get_model_spec("TestNet")
    structs = [r["image"] for r in image_df.collect()]
    batch = imageIO.imageStructsToBatchArray(structs, target_size=None,
                                             dtype=None)
    staged, run = _resize_uniform_batch(batch, spec.input_size, mf)
    want = np.asarray(run.apply_batch(staged, batch_size=8)
                      ).reshape(len(structs), -1)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
    # Independent cross-implementation oracle: the numpy bilinear resize is
    # a distinct implementation from whichever path the transform used
    # (native C++ / device XLA); they agree to uint8 rounding. The 40x36
    # non-square fixture makes an H/W transpose a hard failure here.
    npy = imageIO.resizeBatchArray(batch, spec.input_size)
    want_np = np.asarray(mf.apply_batch(npy, batch_size=8)
                         ).reshape(len(structs), -1)
    np.testing.assert_allclose(got, want_np, rtol=0.1, atol=0.02)


def test_predictor_probabilities_sum_to_one(image_df):
    p = DeepImagePredictor(inputCol="image", outputCol="preds",
                           modelName="TestNet")
    out = p.transform(image_df).collect()
    probs = np.array([r["preds"] for r in out], dtype=np.float32)
    spec = registry.get_model_spec("TestNet")
    assert probs.shape == (5, spec.classes)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-4)


def test_predictor_decode_topk(image_df):
    p = DeepImagePredictor(inputCol="image", outputCol="preds",
                           modelName="TestNet", decodePredictions=True,
                           topK=3)
    out = p.transform(image_df).collect()
    row = out[0]["preds"]
    assert len(row) == 3
    # descending probability, fields present
    probs = [e["probability"] for e in row]
    assert probs == sorted(probs, reverse=True)
    assert all(e["class"] and e["description"] is not None for e in row)
    # raw column dropped
    assert "preds__raw" not in out[0]


def test_unknown_model_name_rejected():
    with pytest.raises(TypeError, match="supported list"):
        DeepImageFeaturizer(inputCol="image", outputCol="f",
                            modelName="NotAModel")


def test_featurizer_param_copy_isolated(image_df):
    f = DeepImageFeaturizer(inputCol="image", outputCol="features",
                            modelName="TestNet")
    g = f.copy({f.batchSize: 2})
    assert g.getBatchSize() == 2
    assert f.getBatchSize() == 64


def test_ingested_named_featurizer_and_persistence(rng, tmp_path):
    """Registry names WITHOUT a Flax definition (r4: DenseNet121,
    EfficientNetB0, MobileNetV3Small, NASNetMobile) serve through generic
    keras ingestion. Keras init is unseeded, so persistence must save the
    actual weights — the reloaded stage reproduces outputs exactly."""
    pytest.importorskip("keras")
    from sparkdl_tpu.ml import load

    rows = [{"image": imageIO.imageArrayToStruct(
        rng.integers(0, 255, size=(64, 64, 3), dtype=np.uint8),
        origin=str(i))} for i in range(3)]
    df = DataFrame.fromRows(
        rows, schema=pa.schema([pa.field("image", imageIO.imageSchema)]),
        numPartitions=1)
    t = DeepImageFeaturizer(inputCol="image", outputCol="f",
                            modelName="MobileNetV3Small", batchSize=2)
    out = t.transform(df).collect()
    feats = np.array([r["f"] for r in out], np.float32)
    assert feats.shape == (3, 576)
    t.save(str(tmp_path / "ingested"))
    t2 = load(str(tmp_path / "ingested"))
    feats2 = np.array([r["f"] for r in t2.transform(df).collect()],
                      np.float32)
    np.testing.assert_allclose(feats2, feats, rtol=1e-5, atol=1e-6)


def test_ingested_model_names_listed():
    from sparkdl_tpu.models import registry

    for name in ("DenseNet121", "EfficientNetB0", "MobileNetV3Small",
                 "NASNetMobile"):
        assert name in registry.SUPPORTED_MODEL_NAMES
        assert registry.is_ingested_model(name)
        spec = registry.get_model_spec(name)
        assert spec.input_size == (224, 224)


def test_ingested_copy_shares_built_model(rng):
    """A paramMap copy of an ingested-name stage reuses the SAME built
    model (keras init is unseeded — a rebuild would emit incompatible
    features)."""
    pytest.importorskip("keras")
    rows = [{"image": imageIO.imageArrayToStruct(
        rng.integers(0, 255, size=(48, 48, 3), dtype=np.uint8))}
        for _ in range(2)]
    df = DataFrame.fromRows(
        rows, schema=pa.schema([pa.field("image", imageIO.imageSchema)]),
        numPartitions=1)
    t = DeepImageFeaturizer(inputCol="image", outputCol="f",
                            modelName="MobileNetV3Small", batchSize=2)
    a = np.array([r["f"] for r in t.transform(df).collect()], np.float32)
    b = np.array([r["f"] for r in t.transform(
        df, {t.batchSize: 4}).collect()], np.float32)
    np.testing.assert_allclose(b, a, rtol=1e-5, atol=1e-6)


def test_ingested_rejects_bad_weights_and_wrong_head(rng, tmp_path):
    from sparkdl_tpu.models import registry

    with pytest.raises(TypeError, match="Cannot resolve weights"):
        registry.build_featurizer("MobileNetV3Small",
                                  weights={"params": {}})
    # a full model (with classifier head) supplied to the featurizer role
    keras = pytest.importorskip("keras")
    full = keras.applications.MobileNetV3Small(
        weights=None, classes=7, input_shape=(224, 224, 3))
    with pytest.raises(ValueError, match="features"):
        registry.build_featurizer("MobileNetV3Small", weights=full)


def test_ingested_custom_graph_persistence(rng, tmp_path):
    """A CUSTOM Keras graph supplied as weights for an ingested name
    (only the output head is validated) must survive save/load — the
    stage persists the model itself via Keras serialization, since
    msgpack weights could not restore a non-canonical architecture."""
    keras = pytest.importorskip("keras")
    from keras import layers as L

    from sparkdl_tpu.ml import load

    custom = keras.Sequential([
        keras.Input((224, 224, 3)),
        L.Conv2D(8, 3, strides=8, padding="same"),
        L.GlobalAveragePooling2D(),
        L.Dense(576)])  # matches MobileNetV3Small's 576-dim contract
    rows = [{"image": imageIO.imageArrayToStruct(
        rng.integers(0, 255, size=(32, 32, 3), dtype=np.uint8))}
        for _ in range(2)]
    df = DataFrame.fromRows(
        rows, schema=pa.schema([pa.field("image", imageIO.imageSchema)]),
        numPartitions=1)
    t = DeepImageFeaturizer(inputCol="image", outputCol="f",
                            modelName="MobileNetV3Small", weights=custom,
                            batchSize=2)
    want = np.array([r["f"] for r in t.transform(df).collect()], np.float32)
    t.save(str(tmp_path / "custom"))
    t2 = load(str(tmp_path / "custom"))
    got = np.array([r["f"] for r in t2.transform(df).collect()], np.float32)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_keras_reference_covers_ingested_names():
    from sparkdl_tpu.models import registry

    ctor = registry._resolve_keras_ctor("DenseNet121")
    assert ctor.__name__ == "DenseNet121"
    with pytest.raises(ValueError, match="counterpart"):
        registry._resolve_keras_ctor("NoSuchNet")


def test_ingested_bf16_saves_full_precision_weights(rng, tmp_path):
    """ADVICE r4: a dtype=bfloat16 ingested stage must persist the
    PRE-cast f32 weights, so reloading the artifact as float32 recovers
    full precision (not bf16-truncated values)."""
    pytest.importorskip("keras")
    import flax.serialization as fser
    import jax
    import jax.numpy as jnp

    from sparkdl_tpu.ml import load

    t = DeepImageFeaturizer(inputCol="image", outputCol="f",
                            modelName="MobileNetV3Small", batchSize=2,
                            dtype=jnp.bfloat16)
    mf = t._model_function("featurize")
    assert hasattr(mf, "float_source")  # survives the preprocess wrap
    t.save(str(tmp_path / "bf16"))
    # the artifact holds float32 leaves, not bf16-truncated ones
    with open(tmp_path / "bf16" / "weights.msgpack", "rb") as f:
        raw = fser.msgpack_restore(f.read())
    float_leaves = [l for l in jax.tree.leaves(raw)
                    if hasattr(l, "dtype") and l.dtype.kind == "f"]
    assert float_leaves and all(
        l.dtype == np.float32 for l in float_leaves), sorted(
        {str(l.dtype) for l in float_leaves})
    # and the saved values equal the pre-cast source exactly
    src = jax.device_get(mf.float_source.variables)
    got_leaves = jax.tree.leaves(raw)
    want_leaves = jax.tree.leaves(src)
    assert len(got_leaves) == len(want_leaves)
    for g, w in zip(got_leaves, want_leaves):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
    # reloaded at f32, the stage serves full-precision outputs
    t32 = load(str(tmp_path / "bf16"))
    t32.setDtype(None)
    rows = [{"image": imageIO.imageArrayToStruct(
        rng.integers(0, 255, size=(64, 64, 3), dtype=np.uint8),
        origin="0")}]
    df = DataFrame.fromRows(
        rows, schema=pa.schema([pa.field("image", imageIO.imageSchema)]),
        numPartitions=1)
    out = t32.transform(df).collect()
    assert np.asarray(out[0]["f"], np.float32).shape == (576,)


def test_r5_zoo_size_variants_registered():
    """r5 zoo widening: size variants of the oracle-proven ingestion
    families. Every name's feature_dim is validated against the KERAS
    model's own headless pooled output width (construction only, no
    forward — a registry-vs-registry comparison would be tautological);
    one representative (the smallest) additionally builds and runs
    end-to-end. Family-level walker correctness is pinned by the oracle
    tests in tests/models/test_keras_oracle.py."""
    pytest.importorskip("keras")
    from sparkdl_tpu.models import registry

    for name in ("DenseNet169", "DenseNet201", "ResNet101V2",
                 "ResNet152V2", "EfficientNetB1", "MobileNetV3Large"):
        assert name in registry.SUPPORTED_MODEL_NAMES
        spec = registry.get_model_spec(name)
        h, w = spec.input_size
        ctor = registry._resolve_keras_ctor(name)
        assert ctor.__name__ == name
        kmodel = ctor(weights=None, include_top=False, pooling="avg",
                      input_shape=(h, w, 3))
        assert kmodel.output_shape[-1] == spec.feature_dim, name
    mf = registry.build_featurizer("MobileNetV3Large", weights="random")
    out = mf.apply_fn(mf.variables,
                      np.zeros((1, 224, 224, 3), np.float32))
    assert out.shape == (1, 960)
