"""StringIndexer / IndexToString — the Spark feature stages around the
reference's flagship pipeline (string labels in, readable predictions
out). Oracles: Spark's ordering rules (frequencyDesc with alphabetical
tie-break), the three handleInvalid policies, round-trips, and the full
indexer → LR → inverse pipeline."""

import numpy as np
import pytest

from sparkdl_tpu.engine.dataframe import DataFrame
from sparkdl_tpu.ml import (
    IndexToString,
    LogisticRegression,
    Pipeline,
    StringIndexer,
    StringIndexerModel,
    load,
)


@pytest.fixture
def fruit_df():
    rows = ([{"fruit": "apple"}] * 3 + [{"fruit": "banana"}] * 3
            + [{"fruit": "cherry"}])
    return DataFrame.fromRows(rows, numPartitions=2)


def test_order_types(fruit_df):
    def labels(order):
        return StringIndexer(inputCol="fruit", outputCol="i",
                             stringOrderType=order).fit(fruit_df).getLabels()

    # frequencyDesc: apple(3) and banana(3) tie -> alphabetical
    assert labels("frequencyDesc") == ["apple", "banana", "cherry"]
    assert labels("frequencyAsc") == ["cherry", "apple", "banana"]
    assert labels("alphabetAsc") == ["apple", "banana", "cherry"]
    assert labels("alphabetDesc") == ["cherry", "banana", "apple"]


def test_transform_indices(fruit_df):
    model = StringIndexer(inputCol="fruit", outputCol="i").fit(fruit_df)
    out = model.transform(fruit_df).collect()
    assert [r["i"] for r in out] == [0.0, 0.0, 0.0, 1.0, 1.0, 1.0, 2.0]


def test_handle_invalid_policies(fruit_df):
    """Spark semantics: unseen labels AND nulls are invalid data —
    error raises, skip drops the row, keep maps to numLabels."""
    model = StringIndexer(inputCol="fruit", outputCol="i").fit(fruit_df)
    unseen = DataFrame.fromRows([{"fruit": "durian"}, {"fruit": "apple"},
                                 {"fruit": None}])
    with pytest.raises(Exception, match="durian|Invalid"):
        model.transform(unseen).collect()
    keep = model.copy({model.handleInvalid: "keep"}).transform(unseen)
    assert [r["i"] for r in keep.collect()] == [3.0, 0.0, 3.0]
    skip = model.copy({model.handleInvalid: "skip"}).transform(unseen)
    assert [r["i"] for r in skip.collect()] == [0.0]
    # fit itself rejects nulls under the default policy
    with_null = DataFrame.fromRows([{"fruit": "a"}, {"fruit": None}])
    with pytest.raises(ValueError, match="NULL"):
        StringIndexer(inputCol="fruit", outputCol="i").fit(with_null)
    assert StringIndexer(inputCol="fruit", outputCol="i",
                         handleInvalid="keep").fit(with_null).getLabels() \
        == ["a"]
    # labels params are type-checked at construction
    with pytest.raises(TypeError, match="list"):
        IndexToString(inputCol="i", outputCol="s", labels="abc")


def test_index_to_string_roundtrip(fruit_df, tmp_path):
    model = StringIndexer(inputCol="fruit", outputCol="i").fit(fruit_df)
    inverse = IndexToString(inputCol="i", outputCol="back",
                            labels=model.getLabels())
    out = inverse.transform(model.transform(fruit_df)).collect()
    for r in out:
        assert r["back"] == r["fruit"]
    # persistence round-trips for all three stages
    model.save(str(tmp_path / "sim"))
    loaded = load(str(tmp_path / "sim"))
    assert isinstance(loaded, StringIndexerModel)
    assert loaded.getLabels() == model.getLabels()
    inverse.save(str(tmp_path / "its"))
    assert load(str(tmp_path / "its")).getLabels() == model.getLabels()
    si = StringIndexer(inputCol="fruit", outputCol="i",
                       stringOrderType="alphabetAsc")
    si.save(str(tmp_path / "si"))
    assert load(str(tmp_path / "si")).getStringOrderType() == "alphabetAsc"


def test_pipeline_with_string_labels(rng):
    """String labels end-to-end: StringIndexer -> LogisticRegression,
    then IndexToString maps predictions back to label strings."""
    x = rng.normal(size=(60, 3)).astype(np.float32)
    y = np.where(x[:, 0] > 0, "pos", "neg")
    df = DataFrame.fromRows(
        [{"features": x[i].tolist(), "cls": str(y[i])} for i in range(60)],
        numPartitions=2)
    pipe = Pipeline(stages=[
        StringIndexer(inputCol="cls", outputCol="label"),
        LogisticRegression(maxIter=100),
    ])
    fitted = pipe.fit(df)
    indexer = fitted.stages[0]
    out = IndexToString(inputCol="prediction", outputCol="pred_cls",
                        labels=indexer.getLabels()).transform(
        fitted.transform(df)).collect()
    acc = np.mean([r["pred_cls"] == r["cls"] for r in out])
    assert acc >= 0.9


def test_vector_assembler():
    from sparkdl_tpu.ml import VectorAssembler

    rows = [{"a": 1.0, "v": [2.0, 3.0], "b": 4},
            {"a": None, "v": [5.0, 6.0], "b": 7}]
    df = DataFrame.fromRows(rows, numPartitions=1)
    va = VectorAssembler(inputCols=["a", "v", "b"], outputCol="features")
    with pytest.raises(Exception, match="NULL"):
        va.transform(df).collect()
    keep = VectorAssembler(inputCols=["a", "v", "b"], outputCol="features",
                           handleInvalid="keep").transform(df).collect()
    assert keep[0]["features"] == [1.0, 2.0, 3.0, 4.0]
    got = keep[1]["features"]
    assert np.isnan(got[0]) and got[1:] == [5.0, 6.0, 7.0]
    skip = VectorAssembler(inputCols=["a", "v", "b"], outputCol="features",
                           handleInvalid="skip").transform(df).collect()
    assert len(skip) == 1 and skip[0]["features"] == [1.0, 2.0, 3.0, 4.0]
    with pytest.raises(KeyError, match="nope"):
        VectorAssembler(inputCols=["nope"], outputCol="f").transform(df) \
            .collect()


def test_one_hot_encoder(tmp_path):
    from sparkdl_tpu.ml import OneHotEncoder, load

    clean = DataFrame.fromRows([{"i": 0.0}, {"i": 1.0}, {"i": 2.0}],
                               numPartitions=2)
    enc = OneHotEncoder(inputCol="i", outputCol="vec", numCategories=3)
    out = enc.transform(clean).collect()
    # dropLast=True (Spark default): last category is all-zeros
    assert [r["vec"] for r in out] == [[1.0, 0.0], [0.0, 1.0], [0.0, 0.0]]
    full = OneHotEncoder(inputCol="i", outputCol="vec", numCategories=3,
                         dropLast=False).transform(clean).collect()
    assert full[2]["vec"] == [0.0, 0.0, 1.0]

    # invalid data (null / out-of-range): error by default, 'keep' widens
    # by an extra category (all-zeros under dropLast, Spark semantics)
    dirty = DataFrame.fromRows([{"i": 0.0}, {"i": None}, {"i": 9.0}])
    with pytest.raises(Exception, match="invalid category"):
        enc.transform(dirty).collect()
    kept = OneHotEncoder(inputCol="i", outputCol="vec", numCategories=3,
                         handleInvalid="keep").transform(dirty).collect()
    assert [r["vec"] for r in kept] == [[1.0, 0.0, 0.0], [0.0, 0.0, 0.0],
                                        [0.0, 0.0, 0.0]]
    kept_full = OneHotEncoder(
        inputCol="i", outputCol="vec", numCategories=3, dropLast=False,
        handleInvalid="keep").transform(dirty).collect()
    assert kept_full[1]["vec"] == [0.0, 0.0, 0.0, 1.0]
    # fractional indices are a wiring mistake — always rejected
    with pytest.raises(Exception, match="not integral"):
        OneHotEncoder(inputCol="i", outputCol="vec", numCategories=3,
                      handleInvalid="keep").transform(
            DataFrame.fromRows([{"i": 1.7}])).collect()
    enc.save(str(tmp_path / "ohe"))
    assert load(str(tmp_path / "ohe")).getNumCategories() == 3


def test_vector_assembler_null_vector_cell_never_kept():
    """A null VECTOR cell has unknown width: 'keep' must raise, not emit
    a ragged single-NaN row."""
    from sparkdl_tpu.ml import VectorAssembler

    import pyarrow as pa

    rows = [{"v": [1.0, 2.0], "b": 1.0}, {"v": None, "b": 2.0}]
    schema = pa.schema([pa.field("v", pa.list_(pa.float64())),
                        pa.field("b", pa.float64())])
    df = DataFrame.fromRows(rows, schema=schema)
    va = VectorAssembler(inputCols=["v", "b"], outputCol="f",
                         handleInvalid="keep")
    with pytest.raises(Exception, match="vector column"):
        va.transform(df).collect()


def test_assembler_in_flagship_pipeline(rng):
    """Mixed tabular + model features assembled for the downstream
    learner — the Spark workflow shape around the featurizer."""
    from sparkdl_tpu.ml import VectorAssembler

    x = rng.normal(size=(60, 2)).astype(np.float32)
    extra = rng.normal(size=60).astype(np.float32)
    y = (x[:, 0] + extra > 0).astype(int)
    df = DataFrame.fromRows(
        [{"emb": x[i].tolist(), "extra": float(extra[i]),
          "label": int(y[i])} for i in range(60)], numPartitions=2)
    pipe = Pipeline(stages=[
        VectorAssembler(inputCols=["emb", "extra"], outputCol="features"),
        LogisticRegression(maxIter=100),
    ])
    out = pipe.fit(df).transform(df).collect()
    acc = np.mean([r["prediction"] == r["label"] for r in out])
    assert acc >= 0.9


def test_assembler_null_element_and_precision():
    from sparkdl_tpu.ml import VectorAssembler

    import pyarrow as pa

    rows = [{"v": [1.0, None], "b": 2.0}, {"v": [3.0, 4.0], "b": 5.0}]
    schema = pa.schema([pa.field("v", pa.list_(pa.float64())),
                        pa.field("b", pa.float64())])
    df = DataFrame.fromRows(rows, schema=schema)
    with pytest.raises(Exception, match="element"):
        VectorAssembler(inputCols=["v", "b"], outputCol="f").transform(df) \
            .collect()
    kept = VectorAssembler(inputCols=["v", "b"], outputCol="f",
                           handleInvalid="keep").transform(df).collect()
    assert np.isnan(kept[0]["f"][1]) and kept[0]["f"][0] == 1.0
    skipped = VectorAssembler(inputCols=["v", "b"], outputCol="f",
                              handleInvalid="skip").transform(df).collect()
    assert len(skipped) == 1 and skipped[0]["f"] == [3.0, 4.0, 5.0]
    # float64 output: int64 ids above 2^24 survive exactly
    big = DataFrame.fromRows([{"id": 16777217, "x": 0.5}])
    out = VectorAssembler(inputCols=["id", "x"], outputCol="f") \
        .transform(big).collect()
    assert out[0]["f"][0] == 16777217.0


def test_one_hot_encoder_nonfinite():
    from sparkdl_tpu.ml import OneHotEncoder

    df = DataFrame.fromRows([{"i": float("nan")}, {"i": 0.0}])
    with pytest.raises(Exception, match="invalid category"):
        OneHotEncoder(inputCol="i", outputCol="v",
                      numCategories=3).transform(df).collect()
    kept = OneHotEncoder(inputCol="i", outputCol="v", numCategories=3,
                         handleInvalid="keep").transform(df).collect()
    assert kept[0]["v"] == [0.0, 0.0, 0.0]  # NaN -> invalid category
    assert kept[1]["v"] == [1.0, 0.0, 0.0]


def test_min_max_scaler(rng, tmp_path):
    from sparkdl_tpu.ml import MinMaxScaler, MinMaxScalerModel

    x = np.column_stack([rng.uniform(-5, 15, 30), np.full(30, 7.0)])
    df = DataFrame.fromRows([{"v": x[i].tolist()} for i in range(30)],
                            numPartitions=3)
    model = MinMaxScaler(inputCol="v", outputCol="s").fit(df)
    out = np.asarray([r["s"] for r in model.transform(df).collect()])
    assert out[:, 0].min() == pytest.approx(0.0)
    assert out[:, 0].max() == pytest.approx(1.0)
    # constant dimension maps to the midpoint (Spark rule)
    np.testing.assert_allclose(out[:, 1], 0.5)
    # custom range + persistence
    m2 = MinMaxScaler(inputCol="v", outputCol="s", min=-1.0,
                      max=1.0).fit(df)
    m2.save(str(tmp_path / "mm"))
    from sparkdl_tpu.ml import load
    out2 = np.asarray([r["s"] for r in
                       load(str(tmp_path / "mm")).transform(df).collect()])
    assert isinstance(load(str(tmp_path / "mm")), MinMaxScalerModel)
    assert out2[:, 0].min() == pytest.approx(-1.0)
    assert out2[:, 0].max() == pytest.approx(1.0)
    with pytest.raises(ValueError, match="min"):
        MinMaxScaler(inputCol="v", outputCol="s", min=2.0, max=1.0).fit(df)
    # NaN/null elements would silently midpoint a dimension — fit raises
    dirty = DataFrame.fromRows([{"v": [1.0, float("nan")]},
                                {"v": [2.0, 3.0]}])
    with pytest.raises(ValueError, match="impute"):
        MinMaxScaler(inputCol="v", outputCol="s").fit(dirty)


def test_imputer(tmp_path):
    from sparkdl_tpu.ml import Imputer, ImputerModel, load

    rows = [{"v": [1.0, 10.0]}, {"v": [3.0, None]}, {"v": None},
            {"v": [5.0, 30.0]}]
    df = DataFrame.fromRows(rows, numPartitions=2)
    model = Imputer(inputCol="v", outputCol="f").fit(df)
    # means over observed values: (1+3+5)/3 = 3, (10+30)/2 = 20
    np.testing.assert_allclose(model.getSurrogates(), [3.0, 20.0])
    out = [r["f"] for r in model.transform(df).collect()]
    assert out[1] == [3.0, 20.0]   # NaN element filled
    assert out[2] == [3.0, 20.0]   # null row filled
    assert out[0] == [1.0, 10.0]   # observed values untouched
    # Spark's percentile_approx(0.5) returns an ACTUAL element: the
    # lower-middle for even counts — dim1 observed [10, 30] -> 10
    med = Imputer(inputCol="v", outputCol="f", strategy="median").fit(df)
    np.testing.assert_allclose(med.getSurrogates(), [3.0, 10.0])
    # inf is a regular value, not missing (Spark): mean becomes inf
    inf_df = DataFrame.fromRows([{"v": [1.0]}, {"v": [float("inf")]}])
    inf_model = Imputer(inputCol="v", outputCol="f").fit(inf_df)
    assert np.isinf(inf_model.getSurrogates()[0])
    model.save(str(tmp_path / "imp"))
    loaded = load(str(tmp_path / "imp"))
    assert isinstance(loaded, ImputerModel)
    np.testing.assert_allclose(loaded.getSurrogates(), [3.0, 20.0])
    with pytest.raises(ValueError, match="NO observed"):
        Imputer(inputCol="v", outputCol="f").fit(
            DataFrame.fromRows([{"v": [None, 1.0]}]))


def test_normalizer_and_binarizer(tmp_path):
    from sparkdl_tpu.ml import Binarizer, Normalizer, load

    df = DataFrame.fromRows([{"v": [3.0, 4.0]}, {"v": [0.0, 0.0]},
                             {"v": None}])
    out = [r["n"] for r in Normalizer(inputCol="v", outputCol="n")
           .transform(df).collect()]
    np.testing.assert_allclose(out[0], [0.6, 0.8])
    assert out[1] == [0.0, 0.0]  # zero rows pass through
    assert out[2] is None
    l1 = Normalizer(inputCol="v", outputCol="n", p=1.0).transform(df) \
        .collect()
    np.testing.assert_allclose(l1[0]["n"], [3 / 7, 4 / 7])
    with pytest.raises(ValueError, match="p must"):
        Normalizer(inputCol="v", outputCol="n", p=0.5).transform(df) \
            .collect()

    sdf = DataFrame.fromRows([{"x": 0.4}, {"x": 0.6}, {"x": None}])
    b = Binarizer(inputCol="x", outputCol="b", threshold=0.5)
    assert [r["b"] for r in b.transform(sdf).collect()] == [0.0, 1.0, None]
    vb = Binarizer(inputCol="v", outputCol="b", threshold=2.0)
    assert vb.transform(df).collect()[0]["b"] == [1.0, 1.0]
    b.save(str(tmp_path / "bin"))
    assert load(str(tmp_path / "bin")).getOrDefault("threshold") == 0.5


def test_sql_transformer_in_pipeline(rng):
    """Spark's SQLTransformer: a SQL statement as a Pipeline stage over
    __THIS__, composing a registered model UDF + WHERE filter with a
    downstream learner."""
    from sparkdl_tpu.core.model_function import ModelFunction, TensorSpec
    from sparkdl_tpu.ml import SQLTransformer
    from sparkdl_tpu.udf import registerTensorUDF

    import jax.numpy as jnp

    mf = ModelFunction(lambda v, x: x * v["s"], {"s": jnp.asarray(3.0)},
                       TensorSpec((None, 2), "float32"), name="triple")
    registerTensorUDF("triple_udf", mf, batchSize=4)
    x = rng.normal(size=(10, 2)).astype(np.float32)
    df = DataFrame.fromRows(
        [{"vec": x[i].tolist(), "keep": i % 2} for i in range(10)],
        numPartitions=2)
    stage = SQLTransformer(
        statement="SELECT triple_udf(vec) AS out, keep FROM __THIS__ "
                  "WHERE keep = 1")
    out = stage.transform(df).collect()
    assert len(out) == 5 and all(r["keep"] == 1 for r in out)
    np.testing.assert_allclose(out[0]["out"], x[1] * 3.0, rtol=1e-6)
    # inside a Pipeline
    pipe = Pipeline(stages=[stage])
    assert len(pipe.fit(df).transform(df).collect()) == 5
    with pytest.raises(ValueError, match="__THIS__"):
        SQLTransformer(statement="SELECT 1 FROM x").transform(df)
    # the scratch view is cleaned up
    from sparkdl_tpu.engine import dataframe as _df
    assert not [v for v in _df._temp_views if v.startswith("sdl_sqlt_")]


def test_normalizer_nan_propagates_and_binarizer_typed():
    from sparkdl_tpu.ml import Binarizer, Normalizer, VectorAssembler

    import pyarrow as pa

    nan_df = DataFrame.fromRows([{"v": [float("nan"), 3.0]}])
    out = Normalizer(inputCol="v", outputCol="n").transform(nan_df) \
        .collect()
    assert all(np.isnan(out[0]["n"]))  # NaN propagates (Spark), no
    # silently un-normalized row

    # Binarizer declares a typed output, so VectorAssembler's
    # vector-column guard fires on null cells downstream
    df = DataFrame.fromRows([{"v": [3.0, 4.0]}, {"v": None}])
    binarized = Binarizer(inputCol="v", outputCol="b",
                          threshold=2.0).transform(df)
    assert pa.types.is_list(binarized.schema.field("b").type)
    va = VectorAssembler(inputCols=["b"], outputCol="f",
                         handleInvalid="keep")
    with pytest.raises(Exception, match="vector column"):
        va.transform(binarized).collect()
