"""StringIndexer / IndexToString — the Spark feature stages around the
reference's flagship pipeline (string labels in, readable predictions
out). Oracles: Spark's ordering rules (frequencyDesc with alphabetical
tie-break), the three handleInvalid policies, round-trips, and the full
indexer → LR → inverse pipeline."""

import numpy as np
import pytest

from sparkdl_tpu.engine.dataframe import DataFrame
from sparkdl_tpu.ml import (
    IndexToString,
    LogisticRegression,
    Pipeline,
    StringIndexer,
    StringIndexerModel,
    load,
)


@pytest.fixture
def fruit_df():
    rows = ([{"fruit": "apple"}] * 3 + [{"fruit": "banana"}] * 3
            + [{"fruit": "cherry"}])
    return DataFrame.fromRows(rows, numPartitions=2)


def test_order_types(fruit_df):
    def labels(order):
        return StringIndexer(inputCol="fruit", outputCol="i",
                             stringOrderType=order).fit(fruit_df).getLabels()

    # frequencyDesc: apple(3) and banana(3) tie -> alphabetical
    assert labels("frequencyDesc") == ["apple", "banana", "cherry"]
    assert labels("frequencyAsc") == ["cherry", "apple", "banana"]
    assert labels("alphabetAsc") == ["apple", "banana", "cherry"]
    assert labels("alphabetDesc") == ["cherry", "banana", "apple"]


def test_transform_indices(fruit_df):
    model = StringIndexer(inputCol="fruit", outputCol="i").fit(fruit_df)
    out = model.transform(fruit_df).collect()
    assert [r["i"] for r in out] == [0.0, 0.0, 0.0, 1.0, 1.0, 1.0, 2.0]


def test_handle_invalid_policies(fruit_df):
    """Spark semantics: unseen labels AND nulls are invalid data —
    error raises, skip drops the row, keep maps to numLabels."""
    model = StringIndexer(inputCol="fruit", outputCol="i").fit(fruit_df)
    unseen = DataFrame.fromRows([{"fruit": "durian"}, {"fruit": "apple"},
                                 {"fruit": None}])
    with pytest.raises(Exception, match="durian|Invalid"):
        model.transform(unseen).collect()
    keep = model.copy({model.handleInvalid: "keep"}).transform(unseen)
    assert [r["i"] for r in keep.collect()] == [3.0, 0.0, 3.0]
    skip = model.copy({model.handleInvalid: "skip"}).transform(unseen)
    assert [r["i"] for r in skip.collect()] == [0.0]
    # fit itself rejects nulls under the default policy
    with_null = DataFrame.fromRows([{"fruit": "a"}, {"fruit": None}])
    with pytest.raises(ValueError, match="NULL"):
        StringIndexer(inputCol="fruit", outputCol="i").fit(with_null)
    assert StringIndexer(inputCol="fruit", outputCol="i",
                         handleInvalid="keep").fit(with_null).getLabels() \
        == ["a"]
    # labels params are type-checked at construction
    with pytest.raises(TypeError, match="list"):
        IndexToString(inputCol="i", outputCol="s", labels="abc")


def test_index_to_string_roundtrip(fruit_df, tmp_path):
    model = StringIndexer(inputCol="fruit", outputCol="i").fit(fruit_df)
    inverse = IndexToString(inputCol="i", outputCol="back",
                            labels=model.getLabels())
    out = inverse.transform(model.transform(fruit_df)).collect()
    for r in out:
        assert r["back"] == r["fruit"]
    # persistence round-trips for all three stages
    model.save(str(tmp_path / "sim"))
    loaded = load(str(tmp_path / "sim"))
    assert isinstance(loaded, StringIndexerModel)
    assert loaded.getLabels() == model.getLabels()
    inverse.save(str(tmp_path / "its"))
    assert load(str(tmp_path / "its")).getLabels() == model.getLabels()
    si = StringIndexer(inputCol="fruit", outputCol="i",
                       stringOrderType="alphabetAsc")
    si.save(str(tmp_path / "si"))
    assert load(str(tmp_path / "si")).getStringOrderType() == "alphabetAsc"


def test_pipeline_with_string_labels(rng):
    """String labels end-to-end: StringIndexer -> LogisticRegression,
    then IndexToString maps predictions back to label strings."""
    x = rng.normal(size=(60, 3)).astype(np.float32)
    y = np.where(x[:, 0] > 0, "pos", "neg")
    df = DataFrame.fromRows(
        [{"features": x[i].tolist(), "cls": str(y[i])} for i in range(60)],
        numPartitions=2)
    pipe = Pipeline(stages=[
        StringIndexer(inputCol="cls", outputCol="label"),
        LogisticRegression(maxIter=100),
    ])
    fitted = pipe.fit(df)
    indexer = fitted.stages[0]
    out = IndexToString(inputCol="prediction", outputCol="pred_cls",
                        labels=indexer.getLabels()).transform(
        fitted.transform(df)).collect()
    acc = np.mean([r["pred_cls"] == r["cls"] for r in out])
    assert acc >= 0.9
