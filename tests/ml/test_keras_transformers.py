"""KerasTransformer / KerasImageFileTransformer oracle tests.

The reference asserted pipeline output == plain keras predict on the same
inputs (SURVEY.md §4 oracle pattern); reproduced here end-to-end through
the engine, including the .h5/.keras file path.
"""

import numpy as np
import pytest

keras = pytest.importorskip("keras")
from keras import layers  # noqa: E402

from sparkdl_tpu.engine.dataframe import DataFrame  # noqa: E402
from sparkdl_tpu.image import imageIO  # noqa: E402
from sparkdl_tpu.ml import KerasImageFileTransformer, KerasTransformer  # noqa: E402


@pytest.fixture(scope="module")
def dense_model():
    m = keras.Sequential([keras.Input((6,)),
                          layers.Dense(10, activation="relu"),
                          layers.Dense(3)])
    return m


def test_keras_transformer_matches_predict(dense_model, rng):
    x = rng.normal(size=(9, 6)).astype(np.float32)
    df = DataFrame.fromColumns({"features": x}, numPartitions=3)
    t = KerasTransformer(inputCol="features", outputCol="out",
                         model=dense_model, batchSize=4)
    got = np.array([r["out"] for r in t.transform(df).collect()],
                   dtype=np.float32)
    want = dense_model.predict(x, verbose=0)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_keras_transformer_from_file(dense_model, rng, tmp_path):
    path = str(tmp_path / "model.keras")
    dense_model.save(path)
    x = rng.normal(size=(4, 6)).astype(np.float32)
    df = DataFrame.fromColumns({"features": x})
    t = KerasTransformer(inputCol="features", outputCol="out", modelFile=path)
    got = np.array([r["out"] for r in t.transform(df).collect()],
                   dtype=np.float32)
    np.testing.assert_allclose(got, dense_model.predict(x, verbose=0),
                               rtol=1e-4, atol=1e-5)


def test_keras_transformer_requires_model():
    t = KerasTransformer(inputCol="a", outputCol="b")
    df = DataFrame.fromColumns({"a": np.zeros((2, 6), dtype=np.float32)})
    with pytest.raises(ValueError, match="model"):
        t.transform(df)


def test_keras_transformer_set_model_invalidates_cache(rng):
    m1 = keras.Sequential([keras.Input((3,)), layers.Dense(1,
                           kernel_initializer="ones", use_bias=False)])
    m2 = keras.Sequential([keras.Input((3,)), layers.Dense(1,
                           kernel_initializer="zeros", use_bias=False)])
    df = DataFrame.fromColumns({"v": np.ones((2, 3), dtype=np.float32)})
    t = KerasTransformer(inputCol="v", outputCol="o", model=m1)
    assert t.transform(df).collect()[0]["o"] == [3.0]
    t.setModel(m2)
    assert t.transform(df).collect()[0]["o"] == [0.0]
    t.setParams(model=m1)
    assert t.transform(df).collect()[0]["o"] == [3.0]


def test_keras_image_file_transformer_end_to_end(tiny_image_dir, rng):
    # tiny CNN over 16x16 inputs
    m = keras.Sequential([keras.Input((16, 16, 3)),
                          layers.Conv2D(4, 3, activation="relu"),
                          layers.GlobalAveragePooling2D(),
                          layers.Dense(2, activation="softmax")])
    files = [str(p) for p in sorted(tiny_image_dir.glob("*.jpg"))]
    df = DataFrame.fromRows([{"uri": f} for f in files], numPartitions=2)
    t = KerasImageFileTransformer(inputCol="uri", outputCol="preds",
                                  model=m, batchSize=2)
    out = t.transform(df).collect()
    got = np.array([r["preds"] for r in out], dtype=np.float32)
    # oracle: decode+resize the same way, then keras predict
    batch = np.stack([
        imageIO.decodeImageFile(f, target_size=(16, 16)).astype(np.float32)
        for f in files])
    want = m.predict(batch, verbose=0)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)
    # the temp loaded-image column must not leak into the output
    assert set(t.transform(df).columns) == {"uri", "preds"}


def test_keras_image_file_transformer_custom_loader(tiny_image_dir):
    m = keras.Sequential([keras.Input((8, 8, 3)),
                          layers.Flatten(), layers.Dense(2)])
    files = [str(p) for p in sorted(tiny_image_dir.glob("*.jpg"))][:2]
    df = DataFrame.fromRows([{"uri": f} for f in files])

    def loader(uri):
        # constant image: output must be identical across rows
        return np.full((8, 8, 3), 7, dtype=np.uint8)

    t = KerasImageFileTransformer(inputCol="uri", outputCol="preds",
                                  model=m, imageLoader=loader)
    out = t.transform(df).collect()
    a, b = (np.array(r["preds"], dtype=np.float32) for r in out)
    np.testing.assert_array_equal(a, b)
