"""KerasImageFileEstimator: end-to-end fit on an image DataFrame.

Oracle criterion (SURVEY.md §4): training must actually learn — the fitted
model separates a trivially-separable image dataset; fitMultiple shares one
decode pass and honors per-map params.
"""

import numpy as np
import pytest

keras = pytest.importorskip("keras")
from keras import layers  # noqa: E402

from sparkdl_tpu.engine.dataframe import DataFrame  # noqa: E402
from sparkdl_tpu.ml import KerasImageFileEstimator  # noqa: E402


@pytest.fixture
def labeled_image_df(tmp_path):
    """Red images labeled 0, green labeled 1 — trivially separable."""
    from PIL import Image

    rng = np.random.default_rng(0)
    rows = []
    for i in range(24):
        label = i % 2
        arr = rng.integers(0, 40, size=(8, 8, 3), dtype=np.uint8)
        arr[..., label] += 180
        p = tmp_path / f"img_{i}.png"
        Image.fromarray(arr).save(p)
        rows.append({"uri": str(p), "label": label})
    return DataFrame.fromRows(rows, numPartitions=3)


def _tiny_cnn():
    return keras.Sequential([
        keras.Input((8, 8, 3)),
        layers.Rescaling(1 / 255.0),
        layers.Flatten(),
        layers.Dense(2, activation="softmax")])


def test_fit_learns_and_model_transforms(labeled_image_df):
    est = KerasImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        model=_tiny_cnn(), kerasOptimizer="adam",
        kerasLoss="categorical_crossentropy",
        kerasFitParams={"epochs": 30, "batch_size": 8,
                        "learning_rate": 0.05, "shuffle": True})
    model = est.fit(labeled_image_df)
    out = model.transform(labeled_image_df).collect()
    preds = np.array([np.argmax(r["preds"]) for r in out])
    labels = np.array([r["label"] for r in out])
    assert (preds == labels).mean() >= 0.9
    assert model.parent is est


def test_fit_sparse_labels(labeled_image_df):
    est = KerasImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        model=_tiny_cnn(), kerasLoss="sparse_categorical_crossentropy",
        kerasFitParams={"epochs": 20, "batch_size": 8,
                        "learning_rate": 0.05})
    model = est.fit(labeled_image_df)
    out = model.transform(labeled_image_df).collect()
    preds = np.array([np.argmax(r["preds"]) for r in out])
    labels = np.array([r["label"] for r in out])
    assert (preds == labels).mean() >= 0.9


def test_fit_multiple_param_maps(labeled_image_df):
    est = KerasImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        model=_tiny_cnn(),
        kerasFitParams={"epochs": 1, "batch_size": 8})
    maps = [
        {est.kerasFitParams: {"epochs": 1, "batch_size": 8, "seed": 1}},
        {est.kerasFitParams: {"epochs": 25, "batch_size": 8,
                              "learning_rate": 0.05, "seed": 1}},
    ]
    models = est.fit(labeled_image_df, maps)
    assert len(models) == 2
    out = models[1].transform(labeled_image_df).collect()
    preds = np.array([np.argmax(r["preds"]) for r in out])
    labels = np.array([r["label"] for r in out])
    assert (preds == labels).mean() >= 0.9


def test_fit_no_decodable_images_raises(tmp_path):
    bad = tmp_path / "bad.png"
    bad.write_bytes(b"junk")
    df = DataFrame.fromRows([{"uri": str(bad), "label": 0}])
    est = KerasImageFileEstimator(inputCol="uri", outputCol="p",
                                  labelCol="label", model=_tiny_cnn())
    with pytest.raises(ValueError, match="decodable"):
        est.fit(df)


def test_fit_mesh_batch_rounding(labeled_image_df):
    """n=24 rows, data axis 8, batch_size 10 → padded to 16, clamped and
    re-rounded so every shard is equal (ADVICE r1 low)."""
    from sparkdl_tpu.core.mesh import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(data=8))
    est = KerasImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        model=_tiny_cnn(), mesh=mesh,
        kerasFitParams={"epochs": 20, "batch_size": 10,
                        "learning_rate": 0.05})
    model = est.fit(labeled_image_df)
    out = model.transform(labeled_image_df).collect()
    preds = np.array([np.argmax(r["preds"]) for r in out])
    labels = np.array([r["label"] for r in out])
    assert (preds == labels).mean() >= 0.9


def test_fit_mesh_dataset_smaller_than_axis_raises(tmp_path):
    from PIL import Image

    from sparkdl_tpu.core.mesh import MeshConfig, make_mesh

    rng = np.random.default_rng(0)
    rows = []
    for i in range(3):  # fewer rows than the 8-way data axis
        p = tmp_path / f"img_{i}.png"
        Image.fromarray(
            rng.integers(0, 255, size=(8, 8, 3), dtype=np.uint8)).save(p)
        rows.append({"uri": str(p), "label": i % 2})
    df = DataFrame.fromRows(rows, numPartitions=1)
    mesh = make_mesh(MeshConfig(data=8))
    est = KerasImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        model=_tiny_cnn(), mesh=mesh,
        kerasFitParams={"epochs": 1, "batch_size": 8})
    with pytest.raises(ValueError, match="data axis"):
        est.fit(df)


def test_fit_binary_head_scalar_labels(labeled_image_df):
    """Dense(1, sigmoid) + binary_crossentropy + (N,) labels — the ADVICE r1
    high-severity silent-broadcast case — must learn."""
    m = keras.Sequential([
        keras.Input((8, 8, 3)),
        layers.Rescaling(1 / 255.0),
        layers.Flatten(),
        layers.Dense(1, activation="sigmoid")])
    est = KerasImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        model=m, kerasLoss="binary_crossentropy",
        kerasFitParams={"epochs": 40, "batch_size": 8,
                        "learning_rate": 0.1})
    model = est.fit(labeled_image_df)
    out = model.transform(labeled_image_df).collect()
    preds = np.array([float(r["preds"][0]) >= 0.5 for r in out])
    labels = np.array([r["label"] for r in out])
    assert (preds == labels).mean() >= 0.9


def test_load_images_internal_batch_equals_per_row(labeled_image_df):
    """Default (native batch) decode path must agree with the per-row
    custom-loader path on every row."""
    from sparkdl_tpu.image import imageIO

    est = KerasImageFileEstimator(
        inputCol="uri", outputCol="p", labelCol="label", model=_tiny_cnn())
    batch_df = est.loadImagesInternal(labeled_image_df, "uri", "img",
                                      target_size=(8, 8))
    per_row = KerasImageFileEstimator(
        inputCol="uri", outputCol="p", labelCol="label", model=_tiny_cnn(),
        imageLoader=lambda uri: imageIO.decodeImageFile(uri,
                                                        target_size=(8, 8)))
    row_df = per_row.loadImagesInternal(labeled_image_df, "uri", "img",
                                        target_size=(8, 8))
    a = [r["img"] for r in batch_df.collect()]
    b = [r["img"] for r in row_df.collect()]
    assert len(a) == len(b) == 24
    for sa, sb in zip(a, b):
        xa = imageIO.imageStructToArray(sa).astype(int)
        xb = imageIO.imageStructToArray(sb).astype(int)
        assert np.abs(xa - xb).max() <= 2  # decoder-family rounding only


def test_streaming_fit_identical_to_collected(labeled_image_df):
    """shuffle=False: the streaming batch sequence equals the collected
    path's, so the trained params must be bit-identical."""
    shared_model = _tiny_cnn()  # same initial weights for both paths

    def make_est(streaming):
        return KerasImageFileEstimator(
            inputCol="uri", outputCol="preds", labelCol="label",
            model=shared_model, kerasOptimizer="sgd",
            kerasLoss="categorical_crossentropy",
            kerasFitParams={"epochs": 3, "batch_size": 8, "shuffle": False,
                            "learning_rate": 0.05, "streaming": streaming})

    m_stream = make_est(True).fit(labeled_image_df)
    m_collect = make_est(False).fit(labeled_image_df)
    ps = m_stream.getModelFunction().variables
    pc = m_collect.getModelFunction().variables
    import jax
    for a, b in zip(jax.tree.leaves(ps), jax.tree.leaves(pc)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_streaming_fit_many_partitions_bounded(labeled_image_df, monkeypatch):
    """Streaming must never materialize the whole frame: cap concurrently
    outstanding computed partitions at the prefetch window."""
    from sparkdl_tpu.engine import dataframe as edf

    in_flight = {"now": 0, "peak": 0}
    real = edf._run_partition

    def tracked(index, batch, ops, cancelled=None):
        in_flight["now"] += 1
        in_flight["peak"] = max(in_flight["peak"], in_flight["now"])
        try:
            return real(index, batch, ops, cancelled)
        finally:
            in_flight["now"] -= 1

    monkeypatch.setattr(edf, "_run_partition", tracked)
    df = labeled_image_df.repartition(12)
    est = KerasImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        model=_tiny_cnn(), kerasOptimizer="sgd",
        kerasLoss="categorical_crossentropy",
        kerasFitParams={"epochs": 2, "batch_size": 4, "shuffle": True})
    est.fit(df)
    # streamPartitions(prefetch=2) => at most prefetch+1 in flight
    assert 0 < in_flight["peak"] <= 3


def test_stream_partitions_does_not_cache(labeled_image_df):
    from sparkdl_tpu.image import imageIO

    df = labeled_image_df.withColumn(
        "h", lambda u: len(u), inputCols=["uri"])
    parts1 = list(df.streamPartitions())
    assert df._materialized is None  # nothing cached
    parts2 = list(df.streamPartitions())
    assert [p.num_rows for p in parts1] == [p.num_rows for p in parts2]


def test_streaming_fit_small_dataset_single_batch(tmp_path):
    """Fewer rows than batch_size: one smaller batch, like the collected
    path's clamp."""
    from PIL import Image

    rng = np.random.default_rng(1)
    rows = []
    for i in range(5):
        arr = rng.integers(0, 255, size=(8, 8, 3), dtype=np.uint8)
        p = tmp_path / f"s{i}.png"
        Image.fromarray(arr).save(p)
        rows.append({"uri": str(p), "label": i % 2})
    df = DataFrame.fromRows(rows, numPartitions=2)
    est = KerasImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        model=_tiny_cnn(), kerasOptimizer="sgd",
        kerasLoss="categorical_crossentropy",
        kerasFitParams={"epochs": 1, "batch_size": 64})
    model = est.fit(df)
    assert model.getModelFunction() is not None


def test_fit_multiple_streaming(labeled_image_df, monkeypatch):
    """kerasFitParams={'streaming': True} on the base estimator makes
    fitMultiple stream every map's fit (bounded memory, no shared decode
    cache) — VERDICT r3 #7."""
    est = KerasImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        model=_tiny_cnn(),
        kerasFitParams={"epochs": 1, "batch_size": 8, "streaming": True})
    collected = []
    monkeypatch.setattr(
        KerasImageFileEstimator, "_collect_arrays",
        lambda self, ds: collected.append(1) or (_ for _ in ()).throw(
            AssertionError("streaming fitMultiple must not collect")))
    maps = [
        {est.kerasFitParams: {"epochs": 1, "batch_size": 8, "seed": 1,
                              "streaming": True}},
        {est.kerasFitParams: {"epochs": 25, "batch_size": 8, "seed": 1,
                              "learning_rate": 0.05, "streaming": True}},
    ]
    models = est.fit(labeled_image_df, maps)
    assert len(models) == 2 and not collected
    out = models[1].transform(labeled_image_df).collect()
    preds = np.array([np.argmax(r["preds"]) for r in out])
    labels = np.array([r["label"] for r in out])
    assert (preds == labels).mean() >= 0.9


def test_shuffle_buffer_param_controls_pool(labeled_image_df):
    """shuffle_buffer deepens the windowed-shuffle pool: with a buffer
    spanning the whole dataset, the first streamed batch draws from every
    partition (seed-deterministic), not just the first one."""
    import sparkdl_tpu.ml.estimator as E

    est = KerasImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        model=_tiny_cnn(),
        kerasFitParams={"epochs": 1, "batch_size": 4, "shuffle": True,
                        "seed": 0, "shuffle_buffer": 16})
    captured = {}
    orig = E._PartitionBatchStream.__init__

    def spy(self, *a, **kw):
        orig(self, *a, **kw)
        captured["buffer"] = self._shuffle_buffer

    est_cls_stream = E._PartitionBatchStream
    try:
        E._PartitionBatchStream.__init__ = spy
        est.fit(labeled_image_df)
    finally:
        est_cls_stream.__init__ = orig
    assert captured["buffer"] == 16


def test_fit_multiple_per_map_streaming(labeled_image_df, monkeypatch):
    """A per-map {'streaming': True} opts that map out of the shared
    decode cache even when the base estimator would collect; an
    all-streaming map list never collects at all."""
    est = KerasImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        model=_tiny_cnn(),
        kerasFitParams={"epochs": 1, "batch_size": 8})
    monkeypatch.setattr(
        KerasImageFileEstimator, "_collect_arrays",
        lambda self, ds: (_ for _ in ()).throw(
            AssertionError("per-map streaming must not collect")))
    maps = [{est.kerasFitParams: {"epochs": 1, "batch_size": 8, "seed": 1,
                                  "streaming": True}}]
    models = est.fit(labeled_image_df, maps)
    assert len(models) == 1


def test_validation_split_history(labeled_image_df):
    """validation_split holds out the tail (collected path) and records
    per-epoch val metrics in model.history (keras-History parity)."""
    est = KerasImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        model=_tiny_cnn(),
        kerasFitParams={"epochs": 3, "batch_size": 8, "seed": 0,
                        "streaming": False, "validation_split": 0.25,
                        "learning_rate": 0.05})
    model = est.fit(labeled_image_df)
    epochs = model.history["epochs"]
    assert len(epochs) == 3
    assert all("val_loss" in e and "val_accuracy" in e for e in epochs)
    # trivially-separable data: validation accuracy must reach 1.0
    assert epochs[-1]["val_accuracy"] >= 0.9
    # learning happened: val loss decreased over training
    assert epochs[-1]["val_loss"] < epochs[0]["val_loss"]


def test_validation_data_streaming(labeled_image_df, rng):
    """Explicit validation_data arrays work on the streaming path too."""
    vx = np.zeros((4, 8, 8, 3), np.float32)
    vx[:2, ..., 0] = 200.0
    vx[2:, ..., 1] = 200.0
    vy = np.array([0, 0, 1, 1])
    est = KerasImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        model=_tiny_cnn(),
        kerasFitParams={"epochs": 2, "batch_size": 8, "seed": 0,
                        "streaming": True, "learning_rate": 0.05,
                        "validation_data": (vx, vy)})
    model = est.fit(labeled_image_df)
    assert len(model.history["epochs"]) == 2
    assert "val_loss" in model.history["epochs"][-1]


def test_validation_split_streaming_raises(labeled_image_df):
    est = KerasImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        model=_tiny_cnn(),
        kerasFitParams={"epochs": 1, "batch_size": 8,
                        "validation_split": 0.25})  # streaming default True
    with pytest.raises(ValueError, match="validation_split"):
        est.fit(labeled_image_df)


def test_verbose_step_metrics(labeled_image_df, capsys):
    est = KerasImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        model=_tiny_cnn(),
        kerasFitParams={"epochs": 1, "batch_size": 8, "seed": 0,
                        "verbose": True})
    model = est.fit(labeled_image_df)
    assert len(model.history["steps"]) == 3  # 24 rows / b8
    assert all("loss" in s for s in model.history["steps"])
    out = capsys.readouterr().out
    assert '"loss"' in out  # JSONL sink wrote step records


def test_checkpoint_dir_resumes(labeled_image_df, tmp_path):
    """A second fit with the same checkpoint_dir restores the final state
    and performs no further steps — params match the first fit exactly."""
    common = {"epochs": 4, "batch_size": 8, "seed": 5, "shuffle": False,
              "learning_rate": 0.05,
              "checkpoint_dir": str(tmp_path / "ckpt"),
              "checkpoint_every": 1}
    est = KerasImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        model=_tiny_cnn(), kerasFitParams=dict(common))
    m1 = est.fit(labeled_image_df)
    p1 = np.concatenate([np.ravel(l) for l in __import__("jax").tree.leaves(
        m1.getModelFunction().variables)])
    m2 = est.fit(labeled_image_df)  # same dir -> resumes at final step
    p2 = np.concatenate([np.ravel(l) for l in __import__("jax").tree.leaves(
        m2.getModelFunction().variables)])
    np.testing.assert_allclose(p2, p1, rtol=1e-6, atol=1e-7)


def test_validation_data_under_mesh_any_size(labeled_image_df, rng):
    """Validation batches need NOT divide the mesh data axis: the eval
    step is unsharded by design (exact metrics over arbitrary val sizes)."""
    from sparkdl_tpu.core.mesh import MeshConfig, make_mesh

    vx = rng.uniform(0, 255, size=(5, 8, 8, 3)).astype(np.float32)  # 5 % 8 != 0
    vy = np.array([0, 1, 0, 1, 0])
    est = KerasImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        model=_tiny_cnn(), mesh=make_mesh(MeshConfig(data=8)),
        kerasFitParams={"epochs": 1, "batch_size": 8, "seed": 0,
                        "learning_rate": 0.05, "validation_data": (vx, vy)})
    model = est.fit(labeled_image_df)
    assert "val_loss" in model.history["epochs"][0]


def test_validation_data_wins_over_split(labeled_image_df, rng):
    """keras precedence: explicit validation_data overrides the split."""
    vx = rng.uniform(0, 255, size=(4, 8, 8, 3)).astype(np.float32)
    vy = np.array([0, 1, 0, 1])
    est = KerasImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        model=_tiny_cnn(),
        kerasFitParams={"epochs": 1, "batch_size": 8, "seed": 0,
                        "streaming": False, "validation_split": 0.5,
                        "validation_data": (vx, vy)})
    model = est.fit(labeled_image_df)
    # all 24 train rows used (no split): 3 full batches of 8
    # and the val metrics come from the 4 explicit rows
    assert "val_loss" in model.history["epochs"][0]
