"""Multi-chip data-parallel INFERENCE through the user-facing API.

The reference's core scale-out path is featurize/predict over all
executors (SURVEY.md §3.1); the rebuild's analog is the mesh ``data``
axis. These tests assert, on the virtual 8-device CPU mesh, that every
user-facing surface (named transformers, generic transformers, UDFs,
fitted estimator models) produces IDENTICAL output sharded vs
single-device — the equality criterion VERDICT r1 set for this feature.
"""

import numpy as np
import pytest

from sparkdl_tpu.core.mesh import (
    MeshConfig,
    get_default_mesh,
    make_mesh,
    set_default_mesh,
    use_mesh,
)
from sparkdl_tpu.engine.dataframe import DataFrame
from sparkdl_tpu.image import imageIO
from sparkdl_tpu.ml import DeepImageFeaturizer, TPUImageTransformer, TPUTransformer


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(MeshConfig(data=8))


@pytest.fixture
def image_df(rng):
    rows = []
    for i in range(13):  # deliberately not a multiple of 8
        arr = rng.integers(0, 255, size=(32, 32, 3), dtype=np.uint8)
        rows.append({"image": imageIO.imageArrayToStruct(arr, origin=str(i)),
                     "idx": i})
    schema = None
    import pyarrow as pa

    schema = pa.schema([pa.field("image", imageIO.imageSchema),
                        pa.field("idx", pa.int64())])
    return DataFrame.fromRows(rows, schema=schema, numPartitions=3)


def _featurize(df, mesh):
    t = DeepImageFeaturizer(inputCol="image", outputCol="features",
                            modelName="TestNet", batchSize=8, mesh=mesh)
    out = t.transform(df).collect()
    return np.stack([np.asarray(r["features"]) for r in out])


def test_featurizer_mesh_matches_single_device(image_df, mesh8):
    single = _featurize(image_df, None)
    sharded = _featurize(image_df, mesh8)
    np.testing.assert_allclose(sharded, single, rtol=1e-6, atol=1e-6)
    assert single.shape[0] == 13


def test_tensor_transformer_mesh_matches_single_device(rng, mesh8):
    from sparkdl_tpu.core.model_function import ModelFunction, TensorSpec

    w = rng.normal(size=(6, 4)).astype(np.float32)
    mf = ModelFunction.fromFunction(
        lambda vs, x: np.tanh(1.0) * (x @ vs["w"]), {"w": w},
        TensorSpec((None, 6)))
    x = rng.normal(size=(11, 6)).astype(np.float32)
    df = DataFrame.fromColumns({"x": x}, numPartitions=2)

    def run(mesh):
        t = TPUTransformer(inputCol="x", outputCol="y", modelFunction=mf,
                           batchSize=4, mesh=mesh)
        out = t.transform(df).collect()
        return np.stack([np.asarray(r["y"]) for r in out])

    np.testing.assert_allclose(run(mesh8), run(None), rtol=1e-6, atol=1e-6)


def test_default_mesh_fallback(image_df, mesh8):
    """set_default_mesh makes every transformer multi-chip without params."""
    single = _featurize(image_df, None)
    assert get_default_mesh() is None
    try:
        set_default_mesh(mesh8)
        sharded = _featurize(image_df, None)
    finally:
        set_default_mesh(None)
    np.testing.assert_allclose(sharded, single, rtol=1e-6, atol=1e-6)


def test_use_mesh_context_manager(image_df, mesh8):
    single = _featurize(image_df, None)
    with use_mesh(mesh8):
        sharded = _featurize(image_df, None)
    assert get_default_mesh() is None
    np.testing.assert_allclose(sharded, single, rtol=1e-6, atol=1e-6)


def test_udf_serving_mesh_matches_single_device(image_df, mesh8):
    from sparkdl_tpu.models import registry
    from sparkdl_tpu.udf import registerImageUDF, udf_registry

    mf = registry.build_featurizer("TestNet")
    try:
        registerImageUDF("mesh_feat", mf, batchSize=8, mesh=mesh8)
        registerImageUDF("plain_feat", mf, batchSize=8)
        sharded = image_df.selectExpr("mesh_feat(image) as f").collect()
        single = image_df.selectExpr("plain_feat(image) as f").collect()
    finally:
        udf_registry.unregister("mesh_feat")
        udf_registry.unregister("plain_feat")
    a = np.stack([np.asarray(r["f"]) for r in sharded])
    b = np.stack([np.asarray(r["f"]) for r in single])
    np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)


def test_estimator_mesh_trained_model_transforms_on_mesh(tmp_path, mesh8):
    """Fitted model inherits the estimator's mesh and transforms correctly."""
    keras = pytest.importorskip("keras")
    from keras import layers
    from PIL import Image

    from sparkdl_tpu.ml import KerasImageFileEstimator

    rng = np.random.default_rng(0)
    rows = []
    for i in range(16):
        label = i % 2
        arr = rng.integers(0, 40, size=(8, 8, 3), dtype=np.uint8)
        arr[..., label] += 180
        p = tmp_path / f"img_{i}.png"
        Image.fromarray(arr).save(p)
        rows.append({"uri": str(p), "label": label})
    df = DataFrame.fromRows(rows, numPartitions=2)
    m = keras.Sequential([
        keras.Input((8, 8, 3)), layers.Rescaling(1 / 255.0),
        layers.Flatten(), layers.Dense(2, activation="softmax")])
    est = KerasImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label", model=m,
        mesh=mesh8,
        kerasFitParams={"epochs": 25, "batch_size": 8,
                        "learning_rate": 0.05})
    fitted = est.fit(df)
    assert fitted.getMesh() is mesh8
    out = fitted.transform(df).collect()
    preds = np.array([np.argmax(r["preds"]) for r in out])
    labels = np.array([r["label"] for r in out])
    assert (preds == labels).mean() >= 0.9


def test_keras_transformer_mesh_matches_single_device(rng, mesh8):
    keras = pytest.importorskip("keras")
    from keras import layers

    from sparkdl_tpu.ml import KerasTransformer

    m = keras.Sequential([keras.Input((6,)),
                          layers.Dense(8, activation="relu"),
                          layers.Dense(3)])
    x = rng.normal(size=(13, 6)).astype(np.float32)  # non-multiple of 8
    df = DataFrame.fromColumns({"features": x}, numPartitions=2)

    def run(mesh):
        t = KerasTransformer(inputCol="features", outputCol="out",
                             model=m, batchSize=8, mesh=mesh)
        return np.array([r["out"] for r in t.transform(df).collect()],
                        dtype=np.float32)

    np.testing.assert_allclose(run(mesh8), run(None), rtol=1e-6, atol=1e-6)


def test_keras_image_file_transformer_mesh_matches_single_device(
        rng, mesh8, tmp_path):
    keras = pytest.importorskip("keras")
    from keras import layers
    from PIL import Image

    from sparkdl_tpu.ml import KerasImageFileTransformer

    m = keras.Sequential([keras.Input((16, 16, 3)),
                          layers.Conv2D(4, 3, activation="relu"),
                          layers.GlobalAveragePooling2D(),
                          layers.Dense(2)])
    uris = []
    for i in range(9):  # non-multiple of 8
        p = tmp_path / f"img{i}.png"
        Image.fromarray(rng.integers(0, 255, size=(16, 16, 3),
                                     dtype=np.uint8)).save(p)
        uris.append("file:" + str(p))
    df = DataFrame.fromColumns({"uri": uris}, numPartitions=2)

    def run(mesh):
        t = KerasImageFileTransformer(inputCol="uri", outputCol="out",
                                      model=m, batchSize=8, mesh=mesh)
        return np.array([r["out"] for r in t.transform(df).collect()],
                        dtype=np.float32)

    np.testing.assert_allclose(run(mesh8), run(None), rtol=1e-6, atol=1e-6)
