"""LogisticRegression: the downstream learner of the reference's flagship
``Pipeline([DeepImageFeaturizer, LogisticRegression])`` workflow
(upstream README example; SURVEY.md §0).

Oracle criteria: convergence to the data-generating decision rule on
separable data, multinomial probability sanity, and the full
featurize->classify pipeline end-to-end — plus persistence round-trips.
"""

import numpy as np
import pyarrow as pa
import pytest

from sparkdl_tpu.engine.dataframe import DataFrame
from sparkdl_tpu.image import imageIO
from sparkdl_tpu.ml import (
    DeepImageFeaturizer,
    LogisticRegression,
    LogisticRegressionModel,
    Pipeline,
    load,
)


@pytest.fixture
def blobs_df(rng):
    """Three well-separated gaussian blobs in 5-D."""
    centers = np.array([[4, 0, 0, 0, 0], [0, 4, 0, 0, 0], [0, 0, 4, 0, 0]],
                       np.float32)
    xs, ys = [], []
    for c in range(3):
        xs.append(rng.normal(size=(40, 5)).astype(np.float32) * 0.4
                  + centers[c])
        ys.extend([c] * 40)
    x = np.concatenate(xs)
    rows = [{"features": x[i].tolist(), "label": int(ys[i])}
            for i in range(len(x))]
    return DataFrame.fromRows(rows, numPartitions=3), x, np.asarray(ys)


def test_fit_separable_converges(blobs_df):
    df, x, y = blobs_df
    lr = LogisticRegression(maxIter=200, regParam=0.0)
    model = lr.fit(df)
    assert model.numClasses == 3
    assert model.numIterations is not None and model.numIterations > 0
    out = model.transform(df).collect()
    preds = np.array([r["prediction"] for r in out])
    assert (preds == y).mean() >= 0.99
    probs = np.array([r["probability"] for r in out])
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-5)
    assert (probs.max(axis=1) > 0.8).mean() > 0.9  # confident on blobs


def test_binary_and_regularization(blobs_df, rng):
    x = rng.normal(size=(80, 4)).astype(np.float32)
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(int)
    rows = [{"features": x[i].tolist(), "label": int(y[i])}
            for i in range(80)]
    df = DataFrame.fromRows(rows, numPartitions=2)
    model = LogisticRegression(maxIter=300).fit(df)
    preds = np.array([r["prediction"]
                      for r in model.transform(df).collect()])
    assert (preds == y).mean() >= 0.95
    # strong L2 shrinks coefficients
    small = LogisticRegression(maxIter=300, regParam=10.0).fit(df)
    assert (np.abs(small.coefficients).max()
            < np.abs(model.coefficients).max() / 2)


def test_null_features_pass_through(blobs_df):
    df, _, _ = blobs_df
    with_null = DataFrame.fromRows(
        [{"features": None, "label": 0}] + df.collect(), numPartitions=2)
    model = LogisticRegression(maxIter=50).fit(with_null)
    out = model.transform(with_null).collect()
    assert out[0]["prediction"] is None and out[0]["probability"] is None
    assert out[1]["prediction"] is not None


def test_featurizer_lr_pipeline_end_to_end(rng, tmp_path):
    """The reference's flagship workflow on this framework: image structs
    -> DeepImageFeaturizer(TestNet) -> LogisticRegression, fitted as ONE
    Pipeline and reloaded from disk."""
    rows = []
    for i in range(24):
        label = i % 2
        arr = rng.integers(0, 40, size=(32, 32, 3), dtype=np.uint8)
        arr[..., label] += 150
        rows.append({"image": imageIO.imageArrayToStruct(arr),
                     "label": label})
    df = DataFrame.fromRows(
        rows, schema=pa.schema([pa.field("image", imageIO.imageSchema),
                                pa.field("label", pa.int64())]),
        numPartitions=2)
    pipe = Pipeline(stages=[
        DeepImageFeaturizer(inputCol="image", outputCol="features",
                            modelName="TestNet", batchSize=8),
        LogisticRegression(maxIter=200),
    ])
    fitted = pipe.fit(df)
    out = fitted.transform(df).collect()
    preds = np.array([r["prediction"] for r in out])
    labels = np.array([r["label"] for r in out])
    assert (preds == labels).mean() >= 0.9

    fitted.save(str(tmp_path / "pipe"))
    reloaded = load(str(tmp_path / "pipe"))
    out2 = reloaded.transform(df).collect()
    preds2 = np.array([r["prediction"] for r in out2])
    np.testing.assert_array_equal(preds2, preds)


def test_unfitted_lr_roundtrip(tmp_path, blobs_df):
    df, _, y = blobs_df
    lr = LogisticRegression(maxIter=150, regParam=0.01, tol=1e-5)
    lr.save(str(tmp_path / "lr"))
    lr2 = load(str(tmp_path / "lr"))
    assert isinstance(lr2, LogisticRegression)
    assert lr2.getMaxIter() == 150 and lr2.getRegParam() == 0.01
    m1, m2 = lr.fit(df), lr2.fit(df)
    np.testing.assert_allclose(m2.coefficients, m1.coefficients,
                               rtol=1e-5, atol=1e-6)


def test_model_roundtrip(tmp_path, blobs_df):
    df, _, y = blobs_df
    model = LogisticRegression(maxIter=100).fit(df)
    model.save(str(tmp_path / "lrm"))
    model2 = load(str(tmp_path / "lrm"))
    assert isinstance(model2, LogisticRegressionModel)
    p1 = [r["prediction"] for r in model.transform(df).collect()]
    p2 = [r["prediction"] for r in model2.transform(df).collect()]
    assert p1 == p2


def test_bad_labels_raise(rng):
    rows = [{"features": [0.0, 1.0], "label": "cat"}]
    df = DataFrame.fromRows(rows)
    with pytest.raises(ValueError, match="numeric class"):
        LogisticRegression(maxIter=5).fit(df)


def test_all_null_partition_transform(blobs_df):
    df, _, _ = blobs_df
    model = LogisticRegression(maxIter=30).fit(df)
    nulls = DataFrame.fromRows([{"features": None, "label": 0},
                                {"features": None, "label": 1}],
                               numPartitions=1)
    out = model.transform(nulls).collect()
    assert all(r["prediction"] is None for r in out)


def test_standardization_scale_equivariance(rng):
    """Spark's standardization contract: with standardization=True and
    regParam>0, rescaling a feature column must not change predictions
    (the penalty applies in unit-std space), and reported coefficients
    come back on the original scale."""
    x = rng.normal(size=(120, 4)).astype(np.float32)
    x[:, 2] *= 0.01  # one tiny-scale feature
    y = (x[:, 0] + 100.0 * x[:, 2] > 0).astype(int)

    def frame(mat):
        return DataFrame.fromRows(
            [{"features": mat[i].tolist(), "label": int(y[i])}
             for i in range(len(mat))], numPartitions=2)

    lr = LogisticRegression(maxIter=300, regParam=0.1)
    model = lr.fit(frame(x))
    scaled = x * np.asarray([10.0, 1.0, 100.0, 1.0], np.float32)
    model_scaled = lr.fit(frame(scaled))
    p1 = np.array([r["probability"]
                   for r in model.transform(frame(x)).collect()])
    p2 = np.array([r["probability"]
                   for r in model_scaled.transform(frame(scaled)).collect()])
    np.testing.assert_allclose(p1, p2, atol=1e-4)
    # coefficients are reported on the ORIGINAL scale: w_scaled * scale = w
    np.testing.assert_allclose(
        model_scaled.coefficients * np.asarray([10, 1, 100, 1])[:, None],
        model.coefficients, rtol=1e-3, atol=1e-4)


def test_standardization_off_differs_under_reg(rng):
    """standardization=False fits in raw feature space, so with uneven
    feature scales and regParam>0 the optimum differs from the
    standardized fit."""
    x = rng.normal(size=(100, 3)).astype(np.float32)
    x[:, 0] *= 20.0
    y = (x[:, 0] / 20.0 + x[:, 1] > 0).astype(int)
    df = DataFrame.fromRows(
        [{"features": x[i].tolist(), "label": int(y[i])}
         for i in range(100)], numPartitions=2)
    on = LogisticRegression(maxIter=300, regParam=0.3).fit(df)
    off = LogisticRegression(maxIter=300, regParam=0.3,
                             standardization=False).fit(df)
    assert not np.allclose(on.coefficients, off.coefficients, rtol=1e-2)
    # both still classify the separable data reasonably
    for model in (on, off):
        preds = np.array([r["prediction"]
                          for r in model.transform(df).collect()])
        assert (preds == y).mean() >= 0.85


def test_weight_col_equals_row_duplication(rng):
    """Spark's weightCol semantics: weight 2 on a row == duplicating it."""
    x = rng.normal(size=(40, 3)).astype(np.float32)
    y = (x[:, 0] > 0).astype(int)

    weighted_rows = [{"features": x[i].tolist(), "label": int(y[i]),
                      "w": 2.0 if i < 10 else 1.0} for i in range(40)]
    dup_rows = ([{"features": x[i].tolist(), "label": int(y[i])}
                 for i in range(40)]
                + [{"features": x[i].tolist(), "label": int(y[i])}
                   for i in range(10)])
    lr_w = LogisticRegression(maxIter=200, regParam=0.1, weightCol="w")
    lr_d = LogisticRegression(maxIter=200, regParam=0.1)
    m_w = lr_w.fit(DataFrame.fromRows(weighted_rows, numPartitions=2))
    m_d = lr_d.fit(DataFrame.fromRows(dup_rows, numPartitions=2))
    np.testing.assert_allclose(m_w.coefficients, m_d.coefficients,
                               rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(m_w.intercept, m_d.intercept,
                               rtol=1e-3, atol=1e-4)
    # negative weights rejected
    bad = [{"features": x[0].tolist(), "label": 0, "w": -1.0}]
    with pytest.raises(ValueError, match="negative"):
        lr_w.fit(DataFrame.fromRows(bad))


def test_thresholds_shift_predictions(blobs_df, tmp_path):
    """Spark's rule: prediction = argmax(p_i / t_i); a tiny threshold on
    one class pulls every prediction toward it; round-trips."""
    from sparkdl_tpu.ml import load

    df, x, y = blobs_df
    base = LogisticRegression(maxIter=100).fit(df)
    # exact rule check on hand-set weights: probs [2/3, 1/3] with
    # thresholds [1.0, 0.4] give p/t = [0.667, 0.833] -> class 1 wins
    # even though argmax alone says class 0
    hand = LogisticRegressionModel(thresholds=[1.0, 0.4])
    hand._set_weights(np.asarray([[0.0], [0.0]], np.float32).T,
                      np.asarray([np.log(2.0), 0.0], np.float32))
    one_row = DataFrame.fromRows([{"features": [0.0]}])
    out = hand.transform(one_row).collect()
    np.testing.assert_allclose(out[0]["probability"], [2 / 3, 1 / 3],
                               rtol=1e-5)
    assert out[0]["prediction"] == 1.0
    # a tiny threshold pulls the bulk of predictions toward class 0
    # (rows whose p0 underflows to exactly 0.0 keep their own class)
    tiny = 1e-9
    biased = LogisticRegression(
        maxIter=100, thresholds=[tiny, 1.0, 1.0]).fit(df)
    preds = np.array([r["prediction"]
                      for r in biased.transform(df).collect()])
    assert (preds == 0.0).mean() > 0.8
    # validation: wrong length / nonpositive
    with pytest.raises(ValueError, match="thresholds"):
        LogisticRegression(thresholds=[1.0, 1.0]).fit(df)
    with pytest.raises(ValueError, match="thresholds"):
        LogisticRegression(thresholds=[0.0, 1.0, 1.0]).fit(df)
    # persistence keeps the rule
    biased.save(str(tmp_path / "thr"))
    loaded = load(str(tmp_path / "thr"))
    lp = np.array([r["prediction"]
                   for r in loaded.transform(df).collect()])
    np.testing.assert_array_equal(lp, preds)
    assert base.getThresholds() is None
