"""Oracle-equivalence tests for TPUImageTransformer / TPUTransformer.

The reference's load-bearing test pattern (SURVEY.md §4): pipeline output
must equal running the same model directly on the same inputs. The oracle
here is plain numpy / direct jax apply on host.
"""

import numpy as np
import pyarrow as pa
import pytest

from sparkdl_tpu.core.model_function import ModelFunction, TensorSpec
from sparkdl_tpu.engine.dataframe import DataFrame
from sparkdl_tpu.image import imageIO
from sparkdl_tpu.ml import TFImageTransformer, TFTransformer, TPUImageTransformer, TPUTransformer


def _linear_model(in_dim=6, out_dim=3, seed=0):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=(in_dim, out_dim)).astype(np.float32)
    b = rng.normal(size=(out_dim,)).astype(np.float32)

    def apply_fn(vs, x):
        return x @ vs["w"] + vs["b"]

    mf = ModelFunction.fromFunction(
        apply_fn, {"w": w, "b": b}, TensorSpec((None, in_dim)))
    return mf, w, b


def _image_model(h=8, w=8, c=3):
    """Per-image channel means — shape-sensitive enough to catch layout bugs."""

    def apply_fn(_vs, x):
        return x.mean(axis=(1, 2))

    return ModelFunction.fromFunction(apply_fn, None, TensorSpec((None, h, w, c)))


@pytest.fixture
def image_df(rng):
    structs = []
    arrays = []
    for i in range(7):
        arr = rng.integers(0, 255, size=(8, 8, 3), dtype=np.uint8)
        arrays.append(arr)
        structs.append(imageIO.imageArrayToStruct(arr, origin=f"img{i}"))
    df = DataFrame.fromRows([{"image": s} for s in structs],
                            schema=pa.schema([pa.field("image", imageIO.imageSchema)]),
                            numPartitions=3)
    return df, arrays


def test_tensor_transformer_matches_oracle():
    mf, w, b = _linear_model()
    x = np.random.default_rng(1).normal(size=(10, 6)).astype(np.float32)
    df = DataFrame.fromColumns({"features": x}, numPartitions=3)
    out = TPUTransformer(inputCol="features", outputCol="preds",
                         modelFunction=mf, batchSize=4).transform(df)
    got = np.array([r["preds"] for r in out.collect()], dtype=np.float32)
    np.testing.assert_allclose(got, x @ w + b, rtol=1e-5, atol=1e-5)


def test_tensor_transformer_scalar_column():
    def apply_fn(_vs, x):
        return x * 2.0

    mf = ModelFunction.fromFunction(apply_fn, None, TensorSpec((None,)))
    df = DataFrame.fromColumns({"v": np.arange(5, dtype=np.float32)})
    out = TPUTransformer(inputCol="v", outputCol="o", modelFunction=mf,
                         batchSize=2).transform(df).collect()
    assert [r["o"] for r in out] == [[0.0], [2.0], [4.0], [6.0], [8.0]]


def test_tensor_transformer_row_length_mismatch_raises():
    mf, _, _ = _linear_model(in_dim=6)
    x = np.zeros((4, 5), dtype=np.float32)
    df = DataFrame.fromColumns({"features": x})
    t = TPUTransformer(inputCol="features", outputCol="o", modelFunction=mf)
    from sparkdl_tpu.engine.dataframe import TaskFailure
    with pytest.raises(TaskFailure, match="elements"):
        t.transform(df).collect()


def test_image_transformer_vector_mode_matches_oracle(image_df):
    df, arrays = image_df
    mf = _image_model()
    t = TPUImageTransformer(inputCol="image", outputCol="feat",
                            modelFunction=mf, batchSize=4)
    got = np.array([r["feat"] for r in t.transform(df).collect()],
                   dtype=np.float32)
    want = np.stack([a.astype(np.float32).mean(axis=(0, 1)) for a in arrays])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-3)


def test_image_transformer_resizes_to_model_input(rng):
    # 16x12 inputs, model wants 8x8: the uniform fast path's resize policy
    # (native host downscale / device bilinear; pixel-center, no antialias)
    # must kick in. Oracle runs the same policy by hand.
    from sparkdl_tpu.ml.image_transformer import _resize_uniform_batch

    arr = rng.integers(0, 255, size=(16, 12, 3), dtype=np.uint8)
    struct = imageIO.imageArrayToStruct(arr)
    df = DataFrame.fromRows([{"image": struct}],
                            schema=pa.schema([pa.field("image", imageIO.imageSchema)]))
    mf = _image_model(8, 8, 3)
    out = TPUImageTransformer(inputCol="image", outputCol="feat",
                              modelFunction=mf).transform(df).collect()
    staged, run = _resize_uniform_batch(arr[None], (8, 8), mf)
    want = np.asarray(run.apply_batch(staged))[0]
    np.testing.assert_allclose(np.array(out[0]["feat"]), want.reshape(-1),
                               rtol=1e-4, atol=1e-3)
    # and the resize really happened: mean within a pixel of PIL's result
    pil = imageIO.resizeImageArray(arr, (8, 8)).astype(np.float32)
    np.testing.assert_allclose(np.array(out[0]["feat"]),
                               pil.mean(axis=(0, 1)), rtol=0.05, atol=2.0)


def test_image_transformer_null_rows_propagate(image_df):
    df, arrays = image_df
    rows = df.collect() + [{"image": None}]
    schema = pa.schema([pa.field("image", imageIO.imageSchema)])
    df2 = DataFrame.fromRows(rows, schema=schema, numPartitions=2)
    mf = _image_model()
    out = TPUImageTransformer(inputCol="image", outputCol="feat",
                              modelFunction=mf).transform(df2).collect()
    assert out[-1]["feat"] is None
    assert all(r["feat"] is not None for r in out[:-1])


def test_image_transformer_image_output_mode(image_df):
    df, arrays = image_df

    def apply_fn(_vs, x):
        return x + 1.0

    mf = ModelFunction.fromFunction(apply_fn, None, TensorSpec((None, 8, 8, 3)))
    t = TPUImageTransformer(inputCol="image", outputCol="out",
                            modelFunction=mf, outputMode="image")
    out = t.transform(df).collect()
    got = imageIO.imageStructToArray(out[0]["out"])
    np.testing.assert_allclose(got, arrays[0].astype(np.float32) + 1.0,
                               rtol=1e-5)
    assert out[0]["out"]["origin"] == "img0"


def test_image_transformer_rejects_bad_output_mode():
    with pytest.raises(TypeError, match="outputMode"):
        TPUImageTransformer(inputCol="a", outputCol="b", outputMode="nope")
    t = TPUImageTransformer(inputCol="a", outputCol="b")
    with pytest.raises(TypeError, match="outputMode"):
        t.setOutputMode("tensor")  # setter path must validate too


def test_missing_input_col_fails_fast():
    df = DataFrame.fromColumns({"a": np.zeros((3, 6), dtype=np.float32)})
    mf, _, _ = _linear_model()
    with pytest.raises(KeyError, match="nope"):
        TPUTransformer(inputCol="nope", outputCol="o",
                       modelFunction=mf).transform(df)
    with pytest.raises(KeyError, match="nope"):
        TPUImageTransformer(inputCol="nope", outputCol="o",
                            modelFunction=_image_model()).transform(df)


def test_all_null_image_partition_yields_nulls():
    schema = pa.schema([pa.field("image", imageIO.imageSchema)])
    df = DataFrame.fromRows([{"image": None}, {"image": None}], schema=schema,
                            numPartitions=1)
    out = TPUImageTransformer(inputCol="image", outputCol="feat",
                              modelFunction=_image_model()).transform(df).collect()
    assert [r["feat"] for r in out] == [None, None]


def test_tensor_transformer_empty_partition():
    x = np.arange(12, dtype=np.float32).reshape(2, 6)
    df = DataFrame.fromColumns({"features": x, "keep": [False, True]},
                               numPartitions=2)
    df = df.filter(lambda k: k, inputCols=["keep"])
    mf, w, b = _linear_model()
    out = TPUTransformer(inputCol="features", outputCol="o",
                         modelFunction=mf).transform(df).collect()
    assert len(out) == 1
    np.testing.assert_allclose(np.array(out[0]["o"], dtype=np.float32),
                               x[1] @ w + b, rtol=1e-5)


def test_reference_alias_names():
    assert TFImageTransformer is TPUImageTransformer
    assert TFTransformer is TPUTransformer


def _two_io_model():
    """2-input / 2-output ModelFunction with a dict input spec."""
    from sparkdl_tpu.core.model_function import ModelFunction, TensorSpec

    def apply_fn(vs, x):
        a, b = x["a"], x["b"]
        return {"sum": a + b, "prod_mean": (a * b).mean(axis=1, keepdims=True)}

    spec = {"a": TensorSpec((None, 4), "float32"),
            "b": TensorSpec((None, 4), "float32")}
    return ModelFunction.fromFunction(apply_fn, None, spec, name="two_io")


def test_tensor_transformer_multi_io(rng):
    mf = _two_io_model()
    a = rng.normal(size=(11, 4)).astype(np.float32)
    b = rng.normal(size=(11, 4)).astype(np.float32)
    df = DataFrame.fromColumns({"colA": a, "colB": b}, numPartitions=3)
    t = TPUTransformer(modelFunction=mf,
                       inputMapping={"colA": "a", "colB": "b"},
                       outputMapping={"sum": "s", "prod_mean": "pm"},
                       batchSize=4)
    out = t.transform(df)
    rows = out.collect()
    assert set(out.columns) == {"colA", "colB", "s", "pm"}
    got_s = np.array([r["s"] for r in rows], dtype=np.float32)
    got_pm = np.array([r["pm"] for r in rows], dtype=np.float32)
    np.testing.assert_allclose(got_s, a + b, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(got_pm, (a * b).mean(axis=1, keepdims=True),
                               rtol=1e-6, atol=1e-6)


def test_tensor_transformer_multi_io_mesh_matches_single(rng):
    from sparkdl_tpu.core.mesh import MeshConfig, make_mesh

    mf = _two_io_model()
    a = rng.normal(size=(13, 4)).astype(np.float32)
    b = rng.normal(size=(13, 4)).astype(np.float32)
    df = DataFrame.fromColumns({"colA": a, "colB": b}, numPartitions=2)

    def run(mesh):
        t = TPUTransformer(modelFunction=mf,
                           inputMapping={"colA": "a", "colB": "b"},
                           outputMapping={"sum": "s"}, batchSize=8, mesh=mesh)
        return np.array([r["s"] for r in t.transform(df).collect()],
                        dtype=np.float32)

    mesh8 = make_mesh(MeshConfig(data=8))
    np.testing.assert_allclose(run(mesh8), run(None), rtol=1e-6, atol=1e-6)


def test_tensor_transformer_multi_io_overwrites_existing_column(rng):
    """outputMapping onto an existing column replaces it in place — the
    declared schema must not carry a duplicate field (ADVICE r3)."""
    mf = _two_io_model()
    a = rng.normal(size=(9, 4)).astype(np.float32)
    b = rng.normal(size=(9, 4)).astype(np.float32)
    df = DataFrame.fromColumns({"colA": a, "colB": b}, numPartitions=2)
    t = TPUTransformer(modelFunction=mf,
                       inputMapping={"colA": "a", "colB": "b"},
                       outputMapping={"sum": "colA", "prod_mean": "pm"},
                       batchSize=4)
    out = t.transform(df)
    assert out.columns == ["colA", "colB", "pm"]
    got = np.array([r["colA"] for r in out.select("colA").collect()],
                   dtype=np.float32)
    np.testing.assert_allclose(got, a + b, rtol=1e-6, atol=1e-6)


def test_tensor_transformer_multi_io_validation(rng):
    mf = _two_io_model()
    df = DataFrame.fromColumns({"colA": rng.normal(size=(3, 4)).astype(np.float32)})
    with pytest.raises(ValueError, match="outputMapping"):
        TPUTransformer(modelFunction=mf,
                       inputMapping={"colA": "a"}).transform(df)
    with pytest.raises(ValueError, match="inputMapping covers no column"):
        TPUTransformer(modelFunction=mf,
                       inputMapping={"colA": "a"},
                       outputMapping={"sum": "s"}).transform(df)
    with pytest.raises(KeyError, match="colB"):
        TPUTransformer(modelFunction=mf,
                       inputMapping={"colA": "a", "colB": "b"},
                       outputMapping={"sum": "s"}).transform(df)
