"""LinearRegression + StandardScaler — the regression-side consumers of
the featurizer. Oracles: the exact closed-form ridge solution computed
independently with numpy, weight==duplication equivalence, Spark's
standardized-penalty semantics, and TVS model selection driven by
RegressionEvaluator."""

import numpy as np
import pytest

from sparkdl_tpu.engine.dataframe import DataFrame
from sparkdl_tpu.ml import (
    LinearRegression,
    LinearRegressionModel,
    ParamGridBuilder,
    Pipeline,
    RegressionEvaluator,
    StandardScaler,
    StandardScalerModel,
    TrainValidationSplit,
    load,
)


def _frame(x, y, w=None):
    rows = []
    for i in range(len(x)):
        r = {"features": x[i].tolist(), "label": float(y[i])}
        if w is not None:
            r["w"] = float(w[i])
        rows.append(r)
    return DataFrame.fromRows(rows, numPartitions=2)


def _numpy_ridge(x, y, reg, std=None):
    """Independent closed-form oracle: centered ridge in (optionally)
    scaled space, coefficients unscaled back."""
    xs = x / std if std is not None else x
    n = len(x)
    xm, ym = xs.mean(axis=0), y.mean()
    xc, yc = xs - xm, y - ym
    beta = np.linalg.solve(xc.T @ xc / n + reg * np.eye(x.shape[1]),
                           xc.T @ yc / n)
    b = ym - xm @ beta
    if std is not None:
        beta = beta / std
    return beta, b


def test_matches_closed_form_oracle(rng):
    x = rng.normal(size=(50, 4)).astype(np.float64)
    beta_true = np.asarray([1.5, -2.0, 0.5, 0.0])
    y = x @ beta_true + 3.0 + rng.normal(size=50) * 0.05
    # reg=0: exact OLS regardless of standardization
    model = LinearRegression().fit(_frame(x, y))
    want_beta, want_b = _numpy_ridge(x, y, 0.0)
    np.testing.assert_allclose(model.coefficients, want_beta,
                               rtol=1e-4, atol=1e-5)
    assert model.intercept == pytest.approx(want_b, rel=1e-4)
    # reg>0 with standardization: penalty applies in unit-std space
    std = x.std(axis=0, ddof=1)
    reg_model = LinearRegression(regParam=0.5).fit(_frame(x, y))
    want_beta, want_b = _numpy_ridge(x, y, 0.5, std=std)
    np.testing.assert_allclose(reg_model.coefficients, want_beta,
                               rtol=1e-4, atol=1e-5)
    # reg>0 without standardization differs
    raw_model = LinearRegression(regParam=0.5,
                                 standardization=False).fit(_frame(x, y))
    want_raw, _ = _numpy_ridge(x, y, 0.5)
    np.testing.assert_allclose(raw_model.coefficients, want_raw,
                               rtol=1e-4, atol=1e-5)
    # prediction column
    out = model.transform(_frame(x, y)).collect()
    preds = np.asarray([r["prediction"] for r in out])
    np.testing.assert_allclose(preds, x @ model.coefficients
                               + model.intercept, rtol=1e-6)


def test_weight_equals_duplication(rng):
    x = rng.normal(size=(30, 3)).astype(np.float64)
    y = x[:, 0] * 2 + rng.normal(size=30) * 0.1
    w = np.where(np.arange(30) < 10, 2.0, 1.0)
    dup_x = np.concatenate([x, x[:10]])
    dup_y = np.concatenate([y, y[:10]])
    m_w = LinearRegression(regParam=0.2, weightCol="w").fit(_frame(x, y, w))
    m_d = LinearRegression(regParam=0.2).fit(_frame(dup_x, dup_y))
    np.testing.assert_allclose(m_w.coefficients, m_d.coefficients,
                               rtol=1e-4, atol=1e-6)
    assert m_w.intercept == pytest.approx(m_d.intercept, abs=1e-5)


def test_persistence_and_nulls(rng, tmp_path):
    x = rng.normal(size=(20, 2))
    y = x[:, 0] + 1.0
    model = LinearRegression().fit(_frame(x, y))
    model.save(str(tmp_path / "lrm"))
    loaded = load(str(tmp_path / "lrm"))
    assert isinstance(loaded, LinearRegressionModel)
    np.testing.assert_allclose(loaded.coefficients, model.coefficients)
    nulls = DataFrame.fromRows([{"features": None, "label": 0.0}])
    assert loaded.transform(nulls).collect()[0]["prediction"] is None
    est = LinearRegression(regParam=0.3, standardization=False)
    est.save(str(tmp_path / "lr"))
    re = load(str(tmp_path / "lr"))
    assert re.getRegParam() == pytest.approx(0.3)
    assert not re.getStandardization()


def test_tvs_selects_over_linear_regression(rng):
    """The tuning layer's regression half, end to end: TVS +
    RegressionEvaluator pick the sane regParam over a crippling one."""
    x = rng.normal(size=(80, 3)).astype(np.float64)
    y = x @ np.asarray([1.0, -1.0, 0.5]) + rng.normal(size=80) * 0.1
    lr = LinearRegression()
    grid = ParamGridBuilder().addGrid(lr.regParam, [0.0, 1000.0]).build()
    tvs = TrainValidationSplit(
        estimator=lr, estimatorParamMaps=grid,
        evaluator=RegressionEvaluator(metricName="rmse"),
        trainRatio=0.7, seed=3)
    model = tvs.fit(_frame(x, y))
    assert model.bestIndex == 0
    assert model.validationMetrics[0] < model.validationMetrics[1]


def test_standard_scaler(rng, tmp_path):
    x = rng.normal(size=(40, 3)) * np.asarray([10.0, 0.1, 1.0]) + 5.0
    df = DataFrame.fromRows([{"v": x[i].tolist()} for i in range(40)],
                            numPartitions=3)
    # Spark defaults: withStd only
    model = StandardScaler(inputCol="v", outputCol="s").fit(df)
    np.testing.assert_allclose(model.getStd(), x.std(axis=0, ddof=1),
                               rtol=1e-9)
    out = np.asarray([r["s"] for r in model.transform(df).collect()])
    np.testing.assert_allclose(out, x / x.std(axis=0, ddof=1), rtol=1e-9)
    # withMean centers too
    full = StandardScaler(inputCol="v", outputCol="s", withMean=True,
                          withStd=True).fit(df)
    out = np.asarray([r["s"] for r in full.transform(df).collect()])
    np.testing.assert_allclose(out.mean(axis=0), 0.0, atol=1e-9)
    np.testing.assert_allclose(out.std(axis=0, ddof=1), 1.0, rtol=1e-9)
    # persistence
    full.save(str(tmp_path / "ssm"))
    loaded = load(str(tmp_path / "ssm"))
    assert isinstance(loaded, StandardScalerModel)
    np.testing.assert_allclose(loaded.getMean(), full.getMean())
    # pipeline: scaler feeding the regressor
    y = (x[:, 0] / 10.0) + rng.normal(size=40) * 0.05
    pdf = DataFrame.fromRows(
        [{"v": x[i].tolist(), "label": float(y[i])} for i in range(40)],
        numPartitions=2)
    pipe = Pipeline(stages=[
        StandardScaler(inputCol="v", outputCol="features", withMean=True),
        LinearRegression(),
    ])
    scored = pipe.fit(pdf).transform(pdf).collect()
    rmse = np.sqrt(np.mean([(r["prediction"] - r["label"]) ** 2
                            for r in scored]))
    assert rmse < 0.1


def test_rank_deficient_min_norm(rng):
    """n < d (transfer-learning shape): fit must return the min-norm
    solution, not NaN (the normal-equations solve would)."""
    x = rng.normal(size=(5, 12)).astype(np.float64)
    y = x[:, 0] * 2.0
    model = LinearRegression(regParam=0.0).fit(_frame(x, y))
    assert np.isfinite(model.coefficients).all()
    preds = np.asarray([r["prediction"] for r in
                        model.transform(_frame(x, y)).collect()])
    np.testing.assert_allclose(preds, y, atol=1e-8)  # interpolates


def test_scaler_rejects_inconsistent_widths():
    from sparkdl_tpu.ml import StandardScaler

    df = DataFrame.fromRows([{"v": [1.0]}] * 4 + [{"v": [1.0, 2.0]}] * 4,
                            numPartitions=2)
    with pytest.raises(ValueError, match="widths"):
        StandardScaler(inputCol="v", outputCol="s").fit(df)
