"""One leg of the durable-recovery kill -9 proof (tests/test_durable_chaos.py).

Usage: python _durable_chaos_child.py <mode> <work_dir>

All three modes build the SAME frame (18 PNGs under ``<work>/imgs``,
6 partitions, partition 0 deterministically poisoned, decode through the
multi-process pool) and stream it with durability on:

- ``baseline``  — durable run in its own journal dir, never killed; its
  output bytes are the bit-identity reference.
- ``killed``    — arms the ``process_kill`` fault (SIGKILL self right
  after the third journal commit) — the process must die mid-stream
  with the decode pool armed and the prefetcher running.
- ``resumed``   — same plan, same journal dir as ``killed``: must serve
  committed partitions from spill, compute only the rest, and pin
  telemetry to the durable run id.
"""

import glob
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def build_frame(work):
    import numpy as np
    import pyarrow as pa

    from sparkdl_tpu.engine import DataFrame
    from sparkdl_tpu.image import imageIO

    paths = sorted(glob.glob(os.path.join(work, "imgs", "*.png")))
    rows = [{"i": i, "blob": open(p, "rb").read()}
            for i, p in enumerate(paths)]
    df = DataFrame.fromRows(rows, numPartitions=6)

    def decode(batch):
        if len(batch) == 0:  # quarantine's zero-row probe
            return pa.array([], pa.float64())
        if batch.column("i")[0].as_py() == 0:
            raise ValueError("poison partition")  # FATAL -> quarantine
        blobs = [b.as_py() for b in batch.column("blob")]
        arrs = imageIO.decodeImageBytesBatch(blobs, (8, 8))
        return pa.array([float(np.asarray(a, dtype=np.float64).sum())
                         for a in arrs])

    return df.withColumnBatch("px", decode, outputType=pa.float64())


def main():
    mode, work = sys.argv[1], sys.argv[2]
    import pyarrow as pa

    from sparkdl_tpu.core import durability
    from sparkdl_tpu.core.resilience import Fault, FaultInjector
    from sparkdl_tpu.core.telemetry import Telemetry
    from sparkdl_tpu.engine import EngineConfig

    durable = os.path.join(
        work, "durable-baseline" if mode == "baseline" else "durable")
    EngineConfig.durable_dir = durable
    EngineConfig.decode_workers = 2
    EngineConfig.quarantine = True

    df = build_frame(work)
    out_path = os.path.join(work, f"rows_{mode}.arrow")

    def run():
        batches = list(df.streamPartitions(prefetch=2))
        with pa.OSFile(out_path, "wb") as sink:
            with pa.ipc.new_stream(sink, batches[0].schema) as w:
                for b in batches:
                    w.write_batch(b)

    if mode == "baseline":
        run()
    elif mode == "killed":
        run_id = durability.pinned_run_id(durable)
        with Telemetry("chaos", out_dir=os.path.join(work, "tel"),
                       export_interval_s=0.05, run_id=run_id):
            with FaultInjector.seeded(0, process_kill=Fault(after=2)):
                run()
        raise SystemExit("killed leg survived: process_kill never fired")
    elif mode == "resumed":
        run_id = durability.pinned_run_id(durable)
        with Telemetry("chaos", out_dir=os.path.join(work, "tel"),
                       export_interval_s=0.05, run_id=run_id):
            run()
    else:
        raise SystemExit(f"unknown mode {mode!r}")


if __name__ == "__main__":
    main()
